"""Benchmark: RS(10,4) erasure-coding throughput on the attached TPU chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "GB/s", "vs_baseline": N, ...}

value      = sustained encode+rebuild data throughput per chip (GB/s of
             data-shard bytes processed; min of encode and worst-case
             4-missing rebuild, the BASELINE.json north-star metric).
vs_baseline= ratio vs the host CPU encoder measured in the same run (the
             stand-in for the reference's AVX2 reedsolomon path on this
             machine).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _roundtrip_latency() -> float:
    """Per-dispatch round-trip cost (the axon tunnel adds ~70ms; real
    local PJRT would be sub-ms). Measured so it can be amortised out."""
    import jax
    import jax.numpy as jnp

    z = jax.device_put(np.zeros((8, 128), np.uint32))
    tiny = jax.jit(lambda x: jnp.sum(x))
    float(tiny(z))
    t0 = time.perf_counter()
    iters = 10
    for _ in range(iters):
        float(tiny(z))
    return (time.perf_counter() - t0) / iters


def _chained_gbs(transform, consts, words, n: int, chain_len: int,
                 rtt: float) -> float:
    """Sustained GB/s of data-shard bytes through the kernel, amortising
    dispatch latency over chain_len dependent kernel invocations inside
    one jit (outputs feed the next step's inputs, preventing CSE)."""
    import jax
    import jax.numpy as jnp

    k = len(words)
    rows = consts.shape[0]

    @jax.jit
    def chain(*w):
        ws = list(w)
        for _ in range(chain_len):
            outs = list(transform(consts, ws))
            ws = (outs + ws)[:k]
        return sum(jnp.sum(x, dtype=jnp.uint32) for x in ws[:rows])

    float(chain(*words))  # compile
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        float(chain(*words))
    dt = (time.perf_counter() - t0) / iters
    per_step = max(dt - rtt, 1e-9) / chain_len
    return k * n / per_step / 1e9


def bench_tpu(n_bytes_per_shard: int = 64 << 20, chain_len: int = 16) -> dict:
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec import gf

    n = n_bytes_per_shard
    k = gf.DATA_SHARDS
    # generate the stripes ON DEVICE: a device_put of 640MB through the
    # axon tunnel takes minutes, while PRNG keys are a few bytes
    make = jax.jit(
        lambda key: jax.random.bits(key, (n // 512, 128), jnp.uint32))
    keys = jax.random.split(jax.random.PRNGKey(0), k)
    words = [make(keys[i]) for i in range(k)]
    jax.block_until_ready(words)
    rtt = _roundtrip_latency()

    from seaweedfs_tpu.ops import gf256_pallas as gp
    from seaweedfs_tpu.ops import gf256_mxu as gm

    enc_coeff = gf.parity_matrix()
    # worst-case rebuild: all 4 lost are data shards, rebuilt from
    # shards 4..13 (6 data + 4 parity)
    reb_coeff = gf.shard_rows([0, 1, 2, 3], list(range(4, 14)))

    # race the two TPU formulations (VPU bitplane kernel vs MXU GF(2)
    # bit-matrix matmul) and take the best per operation
    paths = {
        "vpu": lambda c, ws: gp.gf256_words_transform(
            gf.bitplane_constants(c), ws),
        "mxu": gm.mxu_words_transform,
    }
    detail = {}
    for name, fn in paths.items():
        try:
            detail[f"encode_{name}"] = _chained_gbs(
                fn, enc_coeff, words, n, chain_len, rtt)
            detail[f"rebuild4_{name}"] = _chained_gbs(
                fn, reb_coeff, words, n, chain_len, rtt)
        except Exception as e:  # one path failing must not kill the bench
            detail[f"{name}_error"] = str(e)[:200]
    gbs_enc = max((v for d, v in detail.items()
                   if d.startswith("encode_")), default=0.0)
    gbs_reb = max((v for d, v in detail.items()
                   if d.startswith("rebuild4_")), default=0.0)

    return {"encode_gbs": gbs_enc, "rebuild4_gbs": gbs_reb,
            "dispatch_rtt_ms": rtt * 1e3, "paths": detail,
            "value": min(gbs_enc, gbs_reb)}


def bench_cpu(n_bytes_per_shard: int = 4 << 20) -> tuple[float, str]:
    """Host-baseline: the best available CPU encoder — the native AVX2
    kernel (native/gf256.c, the analog of the reference's reedsolomon
    assembly path) when built, else the numpy table-lookup fallback."""
    from seaweedfs_tpu.ec import gf
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder

    enc = CpuEncoder()
    kind = "native-avx2" if enc.use_native else "numpy"
    rng = np.random.default_rng(7)
    data = [rng.integers(0, 256, n_bytes_per_shard).astype(np.uint8)
            for _ in range(gf.DATA_SHARDS)]
    enc.encode(list(data))  # warm tables
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        enc.encode(list(data))
    dt = (time.perf_counter() - t0) / iters
    return gf.DATA_SHARDS * n_bytes_per_shard / dt / 1e9, kind


def main() -> None:
    import jax

    # the axon sitecustomize force-registers the TPU tunnel regardless of
    # JAX_PLATFORMS in the environment; honor an explicit cpu request via
    # jax.config, which wins because it is read at backend-init time
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    cpu_gbs, cpu_kind = bench_cpu()
    n_env = os.environ.get("SWTPU_BENCH_BYTES")
    if backend == "tpu":
        tpu = bench_tpu(int(n_env) if n_env else 64 << 20)
    else:  # no chip attached: measure the interpret path on tiny shapes
        tpu = bench_tpu(int(n_env) if n_env else 256 << 10, chain_len=1)
    value = tpu["value"]
    try:
        from seaweedfs_tpu.stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.EC_THROUGHPUT.set(value)
    except ImportError:
        pass
    print(json.dumps({
        "metric": "rs_10_4_encode_rebuild_GBps_per_chip",
        "value": round(value, 2),
        "unit": "GB/s",
        "vs_baseline": round(value / cpu_gbs, 2),
        "encode_GBps": round(tpu["encode_gbs"], 2),
        "rebuild4_GBps": round(tpu["rebuild4_gbs"], 2),
        "paths": {d: (round(v, 2) if isinstance(v, float) else v)
                  for d, v in tpu.get("paths", {}).items()},
        "cpu_baseline_GBps": round(cpu_gbs, 3),
        "cpu_baseline_kind": cpu_kind,
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
