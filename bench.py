"""Benchmark: RS(10,4) erasure-coding throughput on the attached TPU chip.

ALWAYS prints exactly ONE JSON line on stdout, no matter what fails:
  {"metric": "...", "value": N, "unit": "GB/s", "vs_baseline": N, ...}

value      = sustained encode+rebuild data throughput per chip (GB/s of
             data-shard bytes processed; min of encode and worst-case
             4-missing rebuild — the BASELINE.json north-star metric).
vs_baseline= ratio vs the host CPU encoder measured in the same run (the
             stand-in for the reference's AVX2 reedsolomon path,
             /root/reference/go.mod:41 klauspost/reedsolomon).

Robustness design (the round-1 bench died in backend init and produced no
number at all):
  * All TPU work runs in a KILLABLE CHILD PROCESS ("python bench.py
    --child") with a wall-clock budget; backend init that hangs (the axon
    tunnel can wedge for minutes) is killed, retried once, then abandoned.
  * The child VERIFIES each kernel path on-device against the CPU oracle
    before timing it — a fast-but-wrong path is never reported.
  * The child measures incrementally (small shapes first) and streams each
    cumulative result as a JSON line; the parent keeps the last complete
    one, so even a mid-measurement kill yields a real number.
  * The parent embeds an "error" field and falls back to the CPU number if
    the TPU path dies entirely.
Progress is logged to stderr so a hang is diagnosable.

Env knobs:
  SWTPU_BENCH_BUDGET_S   total wall-clock budget (default 420)
  SWTPU_BENCH_INIT_S     backend-init timeout per attempt (default 180)
  SWTPU_BENCH_BYTES      max bytes per shard in the largest stage
  JAX_PLATFORMS=cpu      force the CPU interpret path (CI)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

_T0 = time.perf_counter()


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# CPU baseline (parent process; no jax import needed)
# ---------------------------------------------------------------------------


def bench_cpu(n_bytes_per_shard: int = 4 << 20) -> tuple[float, str]:
    """Host baseline: the best available CPU encoder — the native AVX2
    kernel (native/gf256.c, analog of the reference's reedsolomon assembly
    path) when built, else the numpy table-lookup fallback."""
    from seaweedfs_tpu.ec import gf
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder

    enc = CpuEncoder()
    kind = "native-avx2" if enc.use_native else "numpy"
    rng = np.random.default_rng(7)
    data = [rng.integers(0, 256, n_bytes_per_shard).astype(np.uint8)
            for _ in range(gf.DATA_SHARDS)]
    enc.encode(list(data))  # warm tables
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        enc.encode(list(data))
    dt = (time.perf_counter() - t0) / iters
    return gf.DATA_SHARDS * n_bytes_per_shard / dt / 1e9, kind


def bench_degraded_read(n_needles: int = 64, payload: int = 8 << 10,
                        reads: int = 300) -> dict:
    """p50/p99 latency of EcVolume.read_needle with one data shard file
    deleted — every read reconstructs its intervals from the 13 survivors
    (the BASELINE.json config-5 path; store_ec.go:319-373 analog).
    Host-path measurement: small recover intervals route to the CPU
    encoder (EcVolume.SMALL_RECOVER_BYTES), so no device in the loop."""
    import shutil
    import tempfile

    from seaweedfs_tpu.ec import pipeline as ecpl
    from seaweedfs_tpu.ec.ec_volume import EcVolume
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    # CPU encoder throughout: this is a latency benchmark of the storage
    # path, and the parent must never init the device backend (that is
    # the killable child's job)
    enc = CpuEncoder()
    tmp = tempfile.mkdtemp(prefix="swtpu_bench_ec_")
    try:
        v = Volume(tmp, "", 1)
        rng = np.random.default_rng(11)
        for i in range(1, n_needles + 1):
            v.write_needle(Needle(cookie=0x1234, id=i,
                                  data=rng.integers(0, 256, payload)
                                  .astype(np.uint8).tobytes()))
        v.close()
        base = os.path.join(tmp, "1")
        ecpl.write_ec_files(base, encoder=enc)
        ecpl.write_sorted_file_from_idx(base)
        os.remove(base + ".ec00")  # lose a data shard
        ev = EcVolume(tmp, "", 1, encoder=enc)
        lat = []
        for r in range(reads):
            nid = (r % n_needles) + 1
            t0 = time.perf_counter()
            n = ev.read_needle(nid)
            lat.append((time.perf_counter() - t0) * 1e3)
            assert len(n.data) == payload
        ev.close()
        lat.sort()
        return {
            "degraded_read_p50_ms": round(lat[len(lat) // 2], 3),
            "degraded_read_p99_ms": round(lat[int(len(lat) * 0.99)], 3),
            "degraded_read_reads": reads,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# Child: all device work. Streams cumulative JSON results line-by-line.
# ---------------------------------------------------------------------------


def _emit(result: dict) -> None:
    print(json.dumps(result), flush=True)


def _verify_paths_on_device(n_small: int = 256 << 10) -> dict:
    """Encode+rebuild a small slab on device with each kernel path and
    compare byte-for-byte against the CPU oracle (the ec_test.go dual-read
    discipline applied to the kernel itself). Returns {path: True|err}."""
    import jax

    from seaweedfs_tpu.ec import gf
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    from seaweedfs_tpu.ops import gf256_mxu as gm
    from seaweedfs_tpu.ops import gf256_pallas as gp

    rng = np.random.default_rng(3)
    data = [rng.integers(0, 256, n_small).astype(np.uint8)
            for _ in range(gf.DATA_SHARDS)]
    oracle = CpuEncoder(use_native=False)
    reb_coeff = gf.shard_rows([0, 1, 2, 3], list(range(4, 14)))
    full = oracle.encode(list(data))
    want_parity = full[gf.DATA_SHARDS:]
    want_reb = oracle._apply_numpy(reb_coeff, full[4:14])

    # IMPORTANT: verify with the DEFAULT block_bm so the exact pallas_call
    # instantiation (BlockSpec/grid) that gets timed is the one checked;
    # n_small spans >1 grid block to cover the pipelined multi-block path
    words = [jax.device_put(gp.bytes_to_words(d)) for d in data]
    wn = words[0].shape[0] * 512
    reb_in = [jax.device_put(gp.bytes_to_words(full[i]))
              for i in range(4, 14)]
    enc_coeff = gf.parity_matrix()
    paths = {
        "vpu": lambda c, ws: gp.gf256_words_transform(
            gf.bitplane_constants(c), ws),
        "mxu": gm.mxu_words_transform,
    }
    status: dict = {}
    for name, fn in paths.items():
        try:
            got_p = [gp.words_to_bytes(np.asarray(o), n_small)
                     for o in fn(enc_coeff, words)]
            got_r = [gp.words_to_bytes(np.asarray(o), n_small)
                     for o in fn(reb_coeff, reb_in)]
            ok = (all(np.array_equal(g, w)
                      for g, w in zip(got_p, want_parity))
                  and all(np.array_equal(g, w)
                          for g, w in zip(got_r, want_reb)))
            status[name] = True if ok else "MISMATCH vs CPU oracle"
        except Exception as e:  # noqa: BLE001 — one path must not kill both
            status[name] = f"{type(e).__name__}: {e}"[:200]
        _log(f"oracle check {name} ({wn}B/shard): {status[name]}")
    return status


def _roundtrip_latency() -> float:
    """Per-dispatch round-trip cost (the axon tunnel adds ~70ms; local
    PJRT would be sub-ms). Measured so it can be amortised out."""
    import jax
    import jax.numpy as jnp

    z = jax.device_put(np.zeros((8, 128), np.uint32))
    tiny = jax.jit(lambda x: jnp.sum(x))
    float(tiny(z))
    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        float(tiny(z))
    return (time.perf_counter() - t0) / iters


# Physical ceiling for PLAUSIBLE results: v5e HBM bandwidth is ~819 GB/s
# and every kernel step must at least read k*n from HBM, so any measured
# data-GB/s above this is a harness artifact, never a real number. The
# round-3 bench published 83,886,080 "GB/s" because a clamp turned short
# timings into exactly bytes/ns — this bound rejects that entire failure
# class instead of reporting it.
HBM_BOUND_GBPS = 819.0


class ImplausibleResult(Exception):
    pass


def _chained_gbs(transform, consts, words, n: int, chain_len: int,
                 rtt: float, budget_s: float | None = None
                 ) -> tuple[float, float, int]:
    """Sustained GB/s of data-shard bytes through the kernel.

    chain_len dependent kernel invocations run inside one jit (outputs
    feed the next step's inputs, preventing CSE); several chain calls
    are then DISPATCHED AHEAD and blocked on once, so the tunnel's
    round-trip latency amortises across the whole timed region via JAX
    async dispatch instead of being subtracted out.

    Measurement honesty rules (the round-3 verdict's #1):
      * nothing is ever subtracted from a timing — any dispatch overhead
        that async dispatch fails to hide is COUNTED, so the number can
        only understate the kernel;
      * a chain too short to measure is grown, not corrected;
      * any result above the HBM ceiling raises ImplausibleResult.
    budget_s, when given, caps the wall clock this call may spend (a
    degraded tunnel with a multi-second rtt must not eat the whole child
    budget inside one measurement).
    Returns (gbs, total timed seconds, chain_len actually used).
    """
    import jax
    import jax.numpy as jnp

    k = len(words)
    rows = consts.shape[0]
    t_entry = time.perf_counter()

    def spent() -> float:
        return time.perf_counter() - t_entry

    def build(cl):
        @jax.jit
        def chain(*w):
            ws = list(w)
            for _ in range(cl):
                outs = list(transform(consts, ws))
                ws = (outs + ws)[:k]
            return sum(jnp.sum(x, dtype=jnp.uint32) for x in ws[:rows])
        return chain

    for _attempt in range(4):
        used_cl = chain_len  # the length the built chain ACTUALLY runs:
        #                      every timing below divides by this, never
        #                      by a post-growth value
        chain = build(used_cl)
        float(chain(*words))  # compile + warm
        t0 = time.perf_counter()
        float(chain(*words))
        dt1 = time.perf_counter() - t0
        if dt1 > 5 * rtt or used_cl >= 256:
            break
        if budget_s is not None and spent() > budget_s / 3:
            break  # growing further would recompile past the budget
        # chain too short for one dispatch to dominate its own rtt:
        # grow it (bounded) so the async loop below isn't dispatch-bound
        grow = max(2, int(5 * rtt / max(dt1, 1e-6)) + 1)
        chain_len = min(256, used_cl * grow)
        _log(f"  chain too short (dt={dt1 * 1e3:.0f}ms vs rtt="
             f"{rtt * 1e3:.0f}ms); growing chain to {chain_len}")
    # dispatch-ahead: enough chain calls that the timed region spans
    # >= ~10 rtts and ~1s of KERNEL time, blocking only on the last.
    # dt1 is a blocking timing, so it contains one full rtt that the
    # async loop will hide; size iters from the kernel-only estimate or
    # the one amortised rtt drags the reported number down by up to
    # rtt/target. (The subtraction here only SIZES the loop — the
    # reported figure still divides the full measured dt.)
    est_step = max(dt1 - rtt, dt1 / 4, 1e-6)
    target = max(1.0, 10 * rtt)
    iters = max(2, int(target / est_step) + 1)
    if budget_s is not None:
        # the budget cap must use the CONSERVATIVE blocking step time
        # dt1, not est_step: with async dispatch the in-loop deadline
        # below may never fire (dispatches return instantly) and the
        # final sync blocks for iters * real_step
        iters = min(iters,
                    max(2, int(max(budget_s - spent(), 0.0) / dt1) + 1))
    iters = min(iters, 100_000)
    t0 = time.perf_counter()
    r = None
    done = 0
    for _ in range(iters):
        r = chain(*words)
        done += 1
        # hard deadline: est_step can underestimate the real per-call
        # cost (e.g. a transient tunnel stall inflated the rtt probe),
        # so the loop itself must also respect the budget; dividing by
        # the count actually dispatched keeps the figure honest
        if done >= 2 and budget_s is not None and spent() > budget_s:
            break
    float(r)  # single sync point
    dt = time.perf_counter() - t0
    per_step = dt / (done * used_cl)
    gbs = k * n / per_step / 1e9
    if gbs > HBM_BOUND_GBPS:
        raise ImplausibleResult(
            f"{gbs:.0f} GB/s exceeds the {HBM_BOUND_GBPS:.0f} GB/s HBM "
            f"ceiling (dt={dt * 1e3:.1f}ms chain={used_cl} "
            f"iters={done}) — measurement artifact, not reported")
    return gbs, dt, used_cl


def child_main() -> None:
    deadline = _T0 + float(os.environ.get("SWTPU_BENCH_CHILD_S", "300"))

    def left() -> float:
        return deadline - time.perf_counter()

    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # the axon sitecustomize force-registers the TPU tunnel regardless
        # of JAX_PLATFORMS; jax.config wins at backend-init time
        jax.config.update("jax_platforms", "cpu")
    _log("initialising jax backend ...")
    backend = jax.default_backend()
    _log(f"backend up: {backend} devices={jax.devices()}")
    _emit({"stage": "init", "backend": backend})
    # tiny-transfer probe BEFORE any real device work: if the tunnel is
    # up-but-wedged this hangs here (and the parent's init timeout kills
    # a child that has transferred nothing), never mid-large-device_put —
    # the round-4 wedge pattern
    t0 = time.perf_counter()
    probe = jax.device_put(np.arange(256, dtype=np.uint8))
    assert int(jnp.sum(probe.astype(jnp.uint32))) == 255 * 128
    _log(f"tiny-transfer probe ok ({time.perf_counter() - t0:.2f}s)")
    _emit({"stage": "probe", "probe_s": round(time.perf_counter() - t0, 2)})

    from seaweedfs_tpu.ec import gf
    from seaweedfs_tpu.ops import gf256_mxu as gm
    from seaweedfs_tpu.ops import gf256_pallas as gp

    status = _verify_paths_on_device()
    _emit({"stage": "oracle", "paths_verified": status})
    good = [p for p, st in status.items() if st is True]
    if not good:
        _emit({"stage": "done", "error": f"no kernel path passed the "
               f"on-device oracle check: {status}"})
        return

    rtt = _roundtrip_latency()
    _log(f"dispatch rtt {rtt * 1e3:.1f} ms")

    enc_coeff = gf.parity_matrix()
    # worst-case rebuild: all 4 lost are data shards, rebuilt from
    # shards 4..13 (6 data + 4 parity)
    reb_coeff = gf.shard_rows([0, 1, 2, 3], list(range(4, 14)))
    paths = {
        "vpu": lambda c, ws: gp.gf256_words_transform(
            gf.bitplane_constants(c), ws),
        "mxu": gm.mxu_words_transform,
    }

    max_bytes = int(os.environ.get(
        "SWTPU_BENCH_BYTES", str((256 << 20) if backend == "tpu"
                                 else (1 << 20))))
    # chains sized so the timed region dwarfs the ~70ms dispatch rtt even
    # at ~100 GB/s (the adaptive growth in _chained_gbs backstops this)
    stages = [(s, c) for s, c in [
        (4 << 20, 64), (16 << 20, 32), (64 << 20, 32),
        (256 << 20, 16)] if s <= max_bytes]
    if not stages:  # tiny SWTPU_BENCH_BYTES: still measure one stage
        stages = [(max(128 << 10, (max_bytes // (128 << 10)) * (128 << 10)),
                   2)]
    detail: dict = {"dispatch_rtt_ms": round(rtt * 1e3, 1)}

    k = gf.DATA_SHARDS
    speeds: dict[str, float] = {}  # path -> best measured GB/s so far

    def emit_cumulative(n: int) -> None:
        """Stream the best-so-far result after EVERY measurement, so a
        budget kill can never lose numbers that were already measured
        (the round-3 16MB results died exactly that way)."""
        enc = max((v for d, v in detail.items()
                   if d.startswith("encode_") and isinstance(v, float)),
                  default=0.0)
        reb = max((v for d, v in detail.items()
                   if d.startswith("rebuild4_") and isinstance(v, float)),
                  default=0.0)
        stage_res = {"stage": f"measure_{n >> 20}MB", "backend": backend,
                     "encode_GBps": enc, "rebuild4_GBps": reb,
                     "paths": detail}
        if enc > 0 and reb > 0:  # "value" only once BOTH ops are measured
            stage_res["value"] = min(enc, reb)
        _emit(stage_res)

    def gen_words(n: int, seed: int = 0) -> list:
        # generate stripes ON DEVICE: device_put of NxGB through the axon
        # tunnel takes minutes, PRNG keys are a few bytes
        make = jax.jit(
            lambda key: jax.random.bits(key, (n // 512, 128), jnp.uint32))
        words = [make(k_) for k_ in
                 jax.random.split(jax.random.PRNGKey(seed), k)]
        jax.block_until_ready(words)
        return words

    def chain_for(name: str, n: int, default: int) -> int:
        # size the chain from the measured speed so the timed region
        # lands near max(0.7s, 12*rtt) on the first try
        if name not in speeds:
            return default
        per_step = k * n / (speeds[name] * 1e9)
        return min(256, max(4, int(max(0.7, 12 * rtt) / per_step) + 1))

    def run_stage(n: int, chain_len: int) -> None:
        words = gen_words(n)
        best = max(speeds.values(), default=0.0)
        for name in sorted(good, key=lambda p: -speeds.get(p, 1e9)):
            if speeds.get(name, 1e9) < best / 5:
                # this path lost the race decisively at a smaller stage;
                # spend the remaining budget on the winner's curve
                _log(f"skipping {name} at {n >> 20}MB (lost race: "
                     f"{speeds[name]:.1f} vs {best:.1f} GB/s)")
                continue
            cl = chain_for(name, n, chain_len)
            for op, coeff in (("encode", enc_coeff), ("rebuild4", reb_coeff)):
                if left() < 15:
                    return
                try:
                    gbs, dt, used_chain = _chained_gbs(
                        paths[name], coeff, words, n, cl, rtt,
                        budget_s=left() - 10)
                except Exception as e:  # noqa: BLE001
                    detail[f"{op}_{name}_error"] = str(e)[:200]
                    _log(f"{op}/{name} n={n >> 20}MB FAILED: {e}")
                    continue
                key = f"{op}_{name}"
                detail[key] = max(detail.get(key, 0.0), round(gbs, 2))
                detail[f"{key}_{n >> 20}MB"] = round(gbs, 2)
                speeds[name] = max(speeds.get(name, 0.0), gbs)
                _log(f"{op}/{name} n={n >> 20}MB chain={used_chain} "
                     f"dt={dt * 1e3:.0f}ms: {gbs:.2f} GB/s")
                emit_cumulative(n)

    def run_batched() -> None:
        # batched rack-encode config (BASELINE.json 64-volume shape scaled
        # to one chip): V volumes in one launch through the mesh "vol"
        # axis, routed through the same Pallas kernel via shard_map
        try:
            from seaweedfs_tpu.parallel import mesh as pmesh

            m = pmesh.make_mesh(jax.devices()[:1])
            vb, nb = (8, 16 << 20) if backend == "tpu" else (4, 256 << 10)
            nb = min(nb, max_bytes)
            mk = jax.jit(lambda key: jax.random.randint(
                key, (vb, k, nb), 0, 256, jnp.uint8))
            vol_data = mk(jax.random.PRNGKey(1))
            jax.block_until_ready(vol_data)
            jax.block_until_ready(pmesh.batched_encode(m, vol_data))  # compile
            # size the iteration count so the timed loop dwarfs rtt
            t0 = time.perf_counter()
            jax.block_until_ready(pmesh.batched_encode(m, vol_data))
            once = time.perf_counter() - t0
            iters = max(2, int(20 * rtt / max(once, 1e-6)) + 1)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = pmesh.batched_encode(m, vol_data)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / iters
            gbs = vb * k * nb / dt / 1e9
            if gbs > HBM_BOUND_GBPS:
                raise ImplausibleResult(
                    f"batched {gbs:.0f} GB/s exceeds HBM ceiling")
            _log(f"batched encode {vb}x{nb >> 20}MB iters={iters}: "
                 f"{gbs:.2f} GB/s")
            _emit({"stage": "batched", "batched_encode_GBps": round(gbs, 2)})
        except Exception as e:  # noqa: BLE001
            _emit({"stage": "batched",
                   "batched_encode_error": str(e)[:200]})

    def tune_block_bm() -> None:
        """Race the Pallas block size (grid tile height) on the encode
        path — leftover-budget autotune. Results land in detail as
        tune_bm<N>: deliberately OUTSIDE the encode_*/rebuild4_* prefixes
        the headline aggregation reads, so the published score reflects
        only the default kernel configuration; tuning data just informs
        moving the default in a future round."""
        n = min(16 << 20, max_bytes)
        words = gen_words(n, seed=2)
        cl = chain_for("vpu", n, 32)
        for bm in (128, 512, 1024):
            if left() < 40:
                return
            try:
                gbs, dt, used = _chained_gbs(
                    lambda c, ws, _bm=bm: gp.gf256_words_transform(
                        gf.bitplane_constants(c), ws, block_bm=_bm),
                    enc_coeff, words, n, cl, rtt, budget_s=left() - 20)
            except Exception as e:  # noqa: BLE001
                detail[f"tune_bm{bm}_error"] = str(e)[:120]
                continue
            detail[f"tune_bm{bm}"] = round(gbs, 2)
            _log(f"tune bm={bm}: {gbs:.2f} GB/s (default bm=256: "
                 f"{speeds.get('vpu', 0):.2f})")
            emit_cumulative(n)

    def run_crc() -> None:
        """Device CRC32C (ops/crc32c_jax.py GF(2)-matmul formulation,
        SURVEY §2b item 2) vs the host SSE4.2 path — decides whether
        folding checksums into the device pipeline pays."""
        try:
            from seaweedfs_tpu.ops.crc32c_jax import crc32c_batch
            from seaweedfs_tpu.util import crc32c as hostcrc

            bsz, n = (64, 1 << 20) if backend == "tpu" else (4, 64 << 10)
            n = min(n, max(max_bytes, 64 << 10))
            mk = jax.jit(lambda key: jax.random.randint(
                key, (bsz, n), 0, 256, jnp.uint8))
            dev = mk(jax.random.PRNGKey(5))
            jax.block_until_ready(dev)
            # oracle first: a fast-but-wrong checksum is never reported
            got = np.asarray(crc32c_batch(dev[:2, :]))
            host = np.frombuffer(
                np.asarray(dev[:2, :]).tobytes(), np.uint8).reshape(2, n)
            want = [hostcrc.crc32c(r.tobytes()) for r in host]
            if list(got) != want:
                raise RuntimeError("device crc mismatch vs host oracle")
            jax.block_until_ready(crc32c_batch(dev))  # compile
            t0 = time.perf_counter()
            jax.block_until_ready(crc32c_batch(dev))  # warm timing probe
            once = time.perf_counter() - t0
            iters = min(50, max(2, int(max(1.0, 10 * rtt)
                                       / max(once, 1e-4)) + 1))
            t0 = time.perf_counter()
            for _ in range(iters):
                r = crc32c_batch(dev)
            jax.block_until_ready(r)
            dt = (time.perf_counter() - t0) / iters
            gbs = bsz * n / dt / 1e9
            if gbs > HBM_BOUND_GBPS:
                raise ImplausibleResult(f"crc {gbs:.0f} GB/s > HBM bound")
            t0 = time.perf_counter()
            for r_ in host:
                hostcrc.crc32c(r_.tobytes())
            host_gbs = 2 * n / (time.perf_counter() - t0) / 1e9
            _log(f"crc32c device {gbs:.2f} GB/s vs host {host_gbs:.2f}")
            _emit({"stage": "crc", "crc_device_GBps": round(gbs, 3),
                   "crc_host_GBps": round(host_gbs, 3)})
        except Exception as e:  # noqa: BLE001
            _emit({"stage": "crc", "crc_error": str(e)[:200]})

    # schedule: first stage decides the kernel race, then the flagship
    # batched config runs EARLY (round-3 lost it to budget exhaustion at
    # the tail), then the winner's size curve, then block-size autotune
    # with whatever budget remains
    if stages:
        run_stage(*stages[0])
    if left() > 25:
        run_batched()
    for n, chain_len in stages[1:]:
        if left() < 30:
            _log(f"budget exhausted before stage n={n >> 20}MB — stopping")
            break
        run_stage(n, chain_len)
    if left() > 45:
        run_crc()
    if left() > 60 and "vpu" in good and backend == "tpu":
        tune_block_bm()
    _emit({"stage": "done", "backend": backend})


# ---------------------------------------------------------------------------
# Parent: spawn/kill child, merge its stream, ALWAYS print the final line.
# ---------------------------------------------------------------------------


def _run_child(budget_s: float, init_s: float) -> tuple[dict, str | None]:
    """Run the child under a wall-clock budget. Returns (merged result,
    error string or None). Kills the child if it produces nothing within
    init_s (wedged backend init) or overruns budget_s."""
    merged: dict = {}
    err: str | None = None
    env = dict(os.environ, SWTPU_BENCH_CHILD_S=str(max(budget_s - 5, 30)))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    lines: list[str] = []
    lock = threading.Lock()

    def reader() -> None:
        for line in proc.stdout:  # type: ignore[union-attr]
            with lock:
                lines.append(line)

    th = threading.Thread(target=reader, daemon=True)
    th.start()
    start = time.perf_counter()
    saw_output = False
    while True:
        alive = proc.poll() is None
        with lock:
            pending, lines = lines, []
        for line in pending:
            saw_output = True
            try:
                merged.update(json.loads(line))
            except json.JSONDecodeError:
                pass
        waited = time.perf_counter() - start
        if not alive:
            if proc.returncode != 0 and "value" not in merged:
                err = f"child exited rc={proc.returncode}"
            break
        if not saw_output and waited > init_s:
            err = f"backend init produced nothing in {init_s:.0f}s — killed"
            proc.kill()
            break
        if waited > budget_s:
            err = (None if "value" in merged
                   else f"budget {budget_s:.0f}s exceeded — killed")
            _log(f"child overran budget {budget_s:.0f}s; killing "
                 f"(have partial result: {'value' in merged})")
            proc.kill()
            break
        time.sleep(0.5)
    proc.wait(timeout=10)
    # final drain: lines still in the pipe / reader thread when the loop
    # broke (fast child, or kill paths) would otherwise be lost
    th.join(timeout=5)
    with lock:
        pending, lines = lines, []
    for line in pending:
        try:
            merged.update(json.loads(line))
        except json.JSONDecodeError:
            pass
    if err and err.startswith("child exited") and "value" in merged:
        err = None
    return merged, err


def main() -> None:
    budget = float(os.environ.get("SWTPU_BENCH_BUDGET_S", "420"))
    init_s = float(os.environ.get("SWTPU_BENCH_INIT_S", "180"))
    result = {
        "metric": "rs_10_4_encode_rebuild_GBps_per_chip",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
        "backend": "none",
    }
    try:
        cpu_gbs, cpu_kind = bench_cpu()
        result["cpu_baseline_GBps"] = round(cpu_gbs, 3)
        result["cpu_baseline_kind"] = cpu_kind
        _log(f"cpu baseline: {cpu_gbs:.3f} GB/s ({cpu_kind})")
    except Exception as e:  # noqa: BLE001
        cpu_gbs = 0.0
        result["cpu_error"] = f"{type(e).__name__}: {e}"[:300]
        _log(f"cpu baseline FAILED: {e}")

    try:
        dr = bench_degraded_read()
        result.update(dr)
        _log(f"degraded read p50={dr['degraded_read_p50_ms']}ms "
             f"p99={dr['degraded_read_p99_ms']}ms")
    except Exception as e:  # noqa: BLE001
        result["degraded_read_error"] = f"{type(e).__name__}: {e}"[:300]
        _log(f"degraded-read bench FAILED: {e}")

    merged: dict = {}
    err: str | None = None
    try:
        remaining = budget - (time.perf_counter() - _T0)
        merged, err = _run_child(remaining, min(init_s, remaining))
        if err and "value" not in merged:
            remaining = budget - (time.perf_counter() - _T0)
            if remaining > 90:
                _log(f"retrying child once ({err}); {remaining:.0f}s left")
                merged, err2 = _run_child(remaining,
                                          min(init_s, remaining - 30))
                err = err2 or err
    except Exception as e:  # noqa: BLE001 — the JSON line must still print
        err = f"{type(e).__name__}: {e}"[:300]

    if "value" in merged and merged.get("backend") != "none":
        result["backend"] = merged.get("backend", "unknown")
        result["value"] = round(float(merged["value"]), 2)
        for key in ("encode_GBps", "rebuild4_GBps", "paths",
                    "paths_verified", "batched_encode_GBps",
                    "batched_encode_error", "crc_device_GBps",
                    "crc_host_GBps", "crc_error"):
            if key in merged:
                result[key] = merged[key]
        if cpu_gbs > 0:
            result["vs_baseline"] = round(result["value"] / cpu_gbs, 2)
    else:
        # TPU path produced nothing usable: report the CPU number so the
        # bench still yields a real measurement, flagged with the error
        result["backend"] = "cpu-fallback"
        result["value"] = round(cpu_gbs, 2)
        result["vs_baseline"] = 1.0 if cpu_gbs > 0 else 0.0
        if "paths_verified" in merged:
            result["paths_verified"] = merged["paths_verified"]
    if err:
        result["error"] = err
    if merged.get("error"):
        result["error"] = (result.get("error", "") + "; " +
                           merged["error"]).strip("; ")

    try:
        from seaweedfs_tpu.stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.EC_THROUGHPUT.set(result["value"])
    except Exception:  # noqa: BLE001
        pass
    print(json.dumps(result))


if __name__ == "__main__":
    if "--child" in sys.argv[1:]:
        child_main()
    elif "--needle" in sys.argv[1:]:
        # needle data-plane benchmark incl. the -workers sweep
        # (tools/bench_needle.py; BENCH_NEEDLE.md documents results)
        import runpy
        sys.argv = [a for a in sys.argv if a != "--needle"]
        runpy.run_path(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools", "bench_needle.py"),
            run_name="__main__")
    else:
        main()
