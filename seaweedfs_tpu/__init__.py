"""seaweedfs_tpu — a TPU-native distributed object/file store.

A ground-up re-design of the capabilities of SeaweedFS (reference:
/root/reference, ~50k LoC Go) for TPU hardware:

- An O(1)-seek blob store: "needles" packed into append-only "volumes"
  (reference: weed/storage/).
- RS(10,4) Reed-Solomon erasure coding of sealed volumes, with the GF(2^8)
  encode/reconstruct math expressed as a JAX/Pallas bitplane matmul running
  on the TPU MXU/VPU instead of amd64 PSHUFB assembly
  (reference: weed/storage/erasure_coding/ + klauspost/reedsolomon).
- A metadata master with heartbeat-driven topology, rack-aware placement and
  client pubsub (reference: weed/server/master_server.go, weed/topology/).
- A POSIX-ish metadata tier ("filer"), S3 gateway, and WebDAV
  (reference: weed/filer2/, weed/s3api/, weed/server/webdav_server.go).

Layout:
- ec/        GF(256) field math, RS matrices, encoders, stripe locate math
- ops/       Pallas TPU kernels (GF(256) bitplane matmul)
- models/    flagship jittable pipelines (encode / rebuild / degraded read)
- parallel/  device-mesh sharding of batched EC work (shard_map, collectives)
- storage/   needle format, needle maps, volumes, superblock, vacuum
- topology/  cluster model: DataCenter/Rack/DataNode, placement, layouts
- master/    master server: heartbeats, assign, sequencer, pubsub
- server/    volume server / filer server HTTP+RPC frontends
- filer/     filer core: entries, chunk overlay algebra, store plugins
- s3/        S3 REST gateway
- shell/     admin commands (ec.encode / ec.rebuild / ec.balance / ...)
- security/  JWT write tokens, guards
- stats/     metrics
- util/      config, http helpers, crc
- native/    C++ accelerated host components (crc32c, needle map)
"""

__version__ = "0.1.0"
