"""Autopilot maintenance plane: the leader-side observe -> plan ->
execute loop that turns scrub reports and health verdicts into paced
repair, vacuum, replication and cold-tiering actions (ROADMAP item 5's
"close the operations loop" half).

- ``plan``       — pure deterministic planner over frozen snapshots
- ``observe``    — snapshot builder (topology + /debug/scrub +
  /debug/health + heartbeat volume stats)
- ``execute``    — token-bucket-paced, pause-on-page, retrying executor
- ``controller`` — the loop + ``/debug/autopilot`` status surface
"""

from .controller import Autopilot
from .plan import (Action, ClusterSnapshot, CorruptionReport, Deferral,
                   EcVolumeState, NodeState, PlannerConfig, VolumeState,
                   plan)

__all__ = ["Autopilot", "Action", "ClusterSnapshot", "CorruptionReport",
           "Deferral", "EcVolumeState", "NodeState", "PlannerConfig",
           "VolumeState", "plan"]
