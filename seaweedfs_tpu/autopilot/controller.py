"""The autopilot controller: the leader-side observe -> plan -> execute
loop, plus the ``/debug/autopilot`` status surface.

Lifecycle mirrors the scrubber's: the object always exists on the
master (so ``POST /debug/autopilot?run=1`` can force a deterministic
cycle even with the loop off — how tests and the heal soak drive it),
the long-lived loop only runs when ``-autopilot.interval`` > 0, and
the first cycle fires one interval after boot so a restarting cluster
is not greeted by a repair stampede racing its own recovery.

Leader discipline: a follower's loop idles (state ``follower``); a
leader deposed mid-cycle halts its executor — the new leader's
autopilot owns the cluster from its own fresh observation.

Cross-cycle damping lives here, NOT in the planner (which must stay
pure): actions executed recently are cooled down (successes for
``cooldown_s`` — defaulted ABOVE the default scrub interval, because a
repaired-in-place rotten shard keeps appearing in every holder's
stale ``last_cycle`` report until the NEXT scrub pass replaces it, and
re-planning it would delete and regenerate an already-clean shard
every cycle; failures for a shorter window so the next cycle retries
without hot-looping), and every filtered action is journaled as an
``autopilot_defer`` with the reason.
"""

from __future__ import annotations

import asyncio
import collections
import time

import aiohttp

from ..security import tls
from ..util import events, failpoints, glog
from .execute import ActionError, Executor
from .observe import Observer
from .plan import PlannerConfig, plan


class Autopilot:
    """One per master process; active only while that master leads."""

    MAX_HISTORY = 16                # kept cycle reports (/debug surface)
    MAX_DEFER_EVENTS = 20           # journal rows per cycle (bounded)

    def __init__(self, master, *,
                 interval_s: float = 0.0,
                 mbps: float = 16.0,
                 dryrun: bool = False,
                 concurrency: int = 2,
                 tier_backend: str = "",
                 garbage_threshold: float = 0.3,
                 cooldown_s: float = 600.0,
                 failure_cooldown_s: float = 10.0,
                 paging_cache_s: float = 5.0):
        self.master = master
        self.interval_s = interval_s
        self.mbps = mbps
        self.dryrun = dryrun
        self.cfg = PlannerConfig(garbage_threshold=garbage_threshold,
                                 tier_backend=tier_backend)
        self.cooldown_s = cooldown_s
        self.failure_cooldown_s = failure_cooldown_s
        self.paging_cache_s = paging_cache_s
        self.observer = Observer(master)
        self.executor = Executor(self._node_post, mbps=mbps,
                                 concurrency=concurrency,
                                 dryrun=dryrun,
                                 is_leader=lambda: master.is_leader,
                                 paging=self._paging)
        self.state = "idle"
        self.cycles = 0
        self.actions_ok = 0
        self.actions_failed = 0
        self.started_at = time.time()
        self.started_mono = time.monotonic()
        self.last_cycle: dict | None = None
        self.history: collections.deque = collections.deque(
            maxlen=self.MAX_HISTORY)
        self._cooldown: dict[tuple, float] = {}
        self._paging_cached: "tuple[float, bool] | None" = None
        self._cycle_lock = asyncio.Lock()

    # ---- transport + paging hooks for the executor --------------------

    async def _node_post(self, url: str, path: str, params: dict,
                         timeout_s: float = 60.0) -> dict:
        # chaos site: every repair dispatch the executor makes is
        # breakable — an injected fault takes the same retry/fallback
        # path a dead target does
        await failpoints.fail("autopilot.execute")
        async with self.master._http.post(
                tls.url(url, path), params=params,
                timeout=aiohttp.ClientTimeout(
                    total=timeout_s)) as resp:
            try:
                body = await resp.json()
            except (ValueError, aiohttp.ContentTypeError):
                body = {"error": (await resp.text())[:200]}
            if resp.status != 200:
                raise ActionError(f"POST {url}{path}: "
                                  f"{body.get('error', resp.status)}")
            return body

    async def _paging(self) -> bool:
        """Cached fleet-wide page check — consulted before every
        action, so it must not cost a full health fan-out each time."""
        now = time.monotonic()
        if self._paging_cached is not None and \
                now - self._paging_cached[0] < self.paging_cache_s:
            return self._paging_cached[1]
        paging = await self.observer.any_paging()
        self._paging_cached = (now, paging)
        return paging

    # ---- metrics -------------------------------------------------------

    @staticmethod
    def _count(name: str, n: float = 1, labels: tuple = ()) -> None:
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        c = getattr(metrics, name)
        (c.labels(*labels) if labels else c).inc(n)

    # ---- the long-lived loop ------------------------------------------

    async def run(self) -> None:
        """Background task retained by the master and cancelled on
        stop (the orphan-task discipline). First cycle after ONE
        interval — never at boot."""
        while True:
            await asyncio.sleep(self.interval_s)
            if not self.master.is_leader:
                self.state = "follower"
                continue
            try:
                await self.run_cycle()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the maintenance
                # plane must outlive any one cycle's failure, visibly
                glog.warning("autopilot cycle failed: %s: %s",
                             type(e).__name__, e)
                self.state = "error"

    async def run_cycle(self) -> dict:
        """One observe -> plan -> execute pass. Serialized: a forced
        POST ?run=1 racing the background loop must not double-repair
        (or double-charge the repair budget). A raising phase leaves
        state at `error`, never stuck mid-phase (a forced cycle has no
        surrounding loop to reset it)."""
        async with self._cycle_lock:
            try:
                return await self._cycle_locked()
            except Exception:
                self.state = "error"
                raise

    async def _cycle_locked(self) -> dict:
        t0 = time.monotonic()
        self.state = "observing"
        snap, errors = await self.observer.snapshot()
        # prime the executor's pause gate from the same evidence
        self._paging_cached = (time.monotonic(), snap.paging)

        self.state = "planning"
        # chaos site: a broken planner = a visibly failed cycle
        await failpoints.fail("autopilot.plan")
        actions, deferrals = plan(snap, self.cfg)

        # cross-cycle damping: recently-acted keys wait out their
        # cooldown (the repair needs a heartbeat/scrub cycle to
        # become observable; re-planning it would double-repair)
        now = time.monotonic()
        self._cooldown = {k: t for k, t in self._cooldown.items()
                          if t > now}
        runnable, cooled = [], []
        for a in actions:
            (cooled if a.key() in self._cooldown
             else runnable).append(a)

        ledger = [a.to_dict() for a in runnable]
        deferred = [d.to_dict() for d in deferrals] + [
            {"vid": a.vid, "kind": a.kind, "reason": "cooldown"}
            for a in cooled]
        # the cheap counter sees EVERY deferral; only the journal
        # rows (one ring entry each) are capped per cycle
        for row in deferred:
            self._count("AUTOPILOT_DEFERRALS",
                        labels=(row["reason"],))
        for row in deferred[:self.MAX_DEFER_EVENTS]:
            events.record("autopilot_defer", **row)

        # deposed between observe and execute: the snapshot this cycle
        # planned from belongs to a leadership that no longer exists —
        # the successor's autopilot owns the cluster from ITS fresh
        # observation. Halt with nothing executed (the executor's own
        # is_leader gate also halts a deposition that lands mid-queue).
        if not self.master.is_leader:
            self.state = "follower"
            report = {
                "wall_ms": round(time.time() * 1000.0, 3),
                "seconds": round(time.monotonic() - t0, 3),
                "dryrun": self.dryrun,
                "halted": "lost leadership",
                "observed": {"nodes": len(snap.nodes),
                             "volumes": len(snap.volumes),
                             "ec_volumes": len(snap.ec_volumes),
                             "corruptions": len(snap.corruptions),
                             "paging": snap.paging,
                             "errors": errors},
                "planned": ledger, "deferred": deferred, "executed": [],
            }
            self.last_cycle = report
            self.history.append(report)
            return report

        self.state = "executing"
        results = await self.executor.execute(runnable)
        # cooldowns expire relative to when execution FINISHED: a
        # long paced cycle must not eat its own damping window and
        # re-enable the double-repair the cooldown prevents
        done = time.monotonic()
        for a, r in zip(runnable, results):
            if r["status"] in ("ok", "dryrun"):
                self.actions_ok += 1
                self._cooldown[a.key()] = done + self.cooldown_s
            elif r["status"] == "error":
                self.actions_failed += 1
                self._cooldown[a.key()] = \
                    done + self.failure_cooldown_s

        self.cycles += 1
        self._count("AUTOPILOT_CYCLES")
        report = {
            "wall_ms": round(time.time() * 1000.0, 3),
            "seconds": round(time.monotonic() - t0, 3),
            "dryrun": self.dryrun,
            "observed": {
                "nodes": len(snap.nodes),
                "volumes": len(snap.volumes),
                "ec_volumes": len(snap.ec_volumes),
                "corruptions": len(snap.corruptions),
                "paging": snap.paging,
                "errors": errors,
            },
            "planned": ledger,
            "deferred": deferred,
            "executed": results,
        }
        self.last_cycle = report
        self.history.append(report)
        self.state = "idle"
        return report

    # ---- /debug/autopilot ---------------------------------------------

    def status(self) -> dict:
        return {
            "enabled": self.interval_s > 0,
            "leader": self.master.is_leader,
            "dryrun": self.dryrun,
            "state": self.state,
            "interval_s": self.interval_s,
            "budget_mbps": self.mbps,
            "cycles": self.cycles,
            "actions_ok": self.actions_ok,
            "actions_failed": self.actions_failed,
            "bytes_paid": self.executor.bytes_paid,
            "paced_sleep_s": round(self.executor.paced_sleep_s, 3),
            "paused_s": round(self.executor.paused_s, 3),
            "in_flight": list(self.executor.in_flight.values()),
            "cooldown": len(self._cooldown),
            "started_wall": round(self.started_at, 3),
            "uptime_s": round(time.monotonic() - self.started_mono, 1),
            "last_cycle": self.last_cycle,
            "history": list(self.history),
        }
