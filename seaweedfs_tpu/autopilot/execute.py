"""Execute phase: turn a plan into paced, bounded, journaled repairs.

Repair traffic is a first-class consumer of cluster bandwidth (the
Facebook warehouse study, 1309.0186: ~180 TB/day median), so the
executor applies the same discipline the scrubber proved for reads,
now driving writes:

* **cluster-wide token bucket** (``-autopilot.mbps``): every action's
  conservative byte estimate is paid for BEFORE it dispatches, so
  sustained repair I/O can never exceed the operator's budget — the
  heal soak asserts the pacing floor from the ledger;
* **pause-on-page**: before each action the fleet's ``/debug/health``
  verdicts are consulted (cached a few seconds); while anything pages,
  repair parks — it must never bury a foreground incident under
  rebuild traffic. Parking past ``pause_max_s`` defers the rest of the
  cycle instead of wedging the loop;
* **bounded concurrency** + per-action retry/backoff
  (``util/resilience.RetryPolicy``) with ranked fallback targets, so a
  target that refuses (dead, partition-mismatched, full) doesn't kill
  the repair — the next-ranked candidate gets it;
* **leadership halt**: a deposed leader stops dispatching immediately
  (remaining actions come back ``halted``), because the new leader's
  autopilot owns the cluster now;
* **dry-run** (``-autopilot.dryrun``): the exact ledger, nothing sent.

Every outcome is journaled (``autopilot_action`` / ``autopilot_defer``
/ ``autopilot_pause`` events) and counted
(``SeaweedFS_autopilot_*``), so the flight recorder can replay why the
cluster healed the way it did.
"""

from __future__ import annotations

import asyncio
import time

from ..ec.scrub import TokenBucket
from ..util import events, glog
from ..util.resilience import RetryPolicy
from .plan import (KIND_REBUILD, KIND_REPLICATE, KIND_TIER, KIND_VACUUM,
                   Action)

_PAUSE_POLL_S = 1.0


class ActionError(Exception):
    pass


class Executor:
    def __init__(self, node_post, *,
                 mbps: float = 16.0,
                 concurrency: int = 2,
                 dryrun: bool = False,
                 is_leader=None,
                 paging=None,
                 pause_max_s: float = 300.0,
                 sleep=asyncio.sleep):
        """`node_post(url, path, params, timeout_s) -> dict` is the one
        transport hook (controller wires it to the master's session;
        tests inject a recorder). `paging() -> bool` is async."""
        self.node_post = node_post
        self.mbps = mbps
        self.dryrun = dryrun
        self.concurrency = max(1, concurrency)
        self.is_leader = is_leader or (lambda: True)
        self.paging = paging
        self.pause_max_s = pause_max_s
        self._sleep = sleep
        self.bucket = TokenBucket(mbps * (1 << 20), sleep=sleep)
        self.paced_sleep_s = 0.0
        self.paused_s = 0.0
        self.bytes_paid = 0
        self.in_flight: dict = {}

    # ---- metrics (lazy, prometheus-optional) ---------------------------

    @staticmethod
    def _count(name: str, n: float = 1, labels: tuple = ()) -> None:
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        c = getattr(metrics, name)
        (c.labels(*labels) if labels else c).inc(n)

    @staticmethod
    def _gauge(name: str, v: float) -> None:
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        getattr(metrics, name).set(v)

    # ---- the paced dispatch loop ---------------------------------------

    async def execute(self, actions: "list[Action]") -> "list[dict]":
        """Run the ordered plan; returns one result row per action, in
        plan order. Pays the token bucket and consults the pause gate
        SEQUENTIALLY (pacing and priority stay meaningful), then runs
        the network work under bounded concurrency."""
        results: "list[dict]" = [None] * len(actions)  # type: ignore
        sem = asyncio.Semaphore(self.concurrency)
        tasks: "list[asyncio.Task]" = []
        halted_from = len(actions)
        self._gauge("AUTOPILOT_QUEUE_DEPTH", len(actions))
        for i, a in enumerate(actions):
            self._gauge("AUTOPILOT_QUEUE_DEPTH", len(actions) - i)
            if not self.is_leader():
                halted_from = i
                break
            # dry-run executes nothing, so it must also BLOCK on
            # nothing: no token-bucket sleeps (a 30 GB rebuild
            # estimate would park a forced ?run=1 cycle for minutes)
            # and no pause gate — the ledger still rides live order
            if not self.dryrun:
                paused = await self._pause_gate()
                if paused == "defer":
                    halted_from = i
                    for j in range(i, len(actions)):
                        results[j] = self._result(
                            actions[j], "deferred",
                            error="paused too long")
                        events.record("autopilot_defer",
                                      kind=actions[j].kind,
                                      vid=actions[j].vid,
                                      reason="paused-too-long")
                        self._count("AUTOPILOT_DEFERRALS",
                                    labels=("paused",))
                    break
                if not self.is_leader():
                    halted_from = i
                    break
                # pay for the action's bytes BEFORE it moves them
                self.paced_sleep_s += \
                    await self.bucket.consume(a.bytes_est)
                # paid = admitted through the bucket; a dry run admits
                # nothing and must not inflate the budget accounting
                self.bytes_paid += a.bytes_est

            async def run_one(idx: int, act: Action) -> None:
                async with sem:
                    results[idx] = await self._run_action(act)
            t = asyncio.ensure_future(run_one(i, a))
            tasks.append(t)
        if tasks:
            await asyncio.gather(*tasks)
        for j in range(halted_from, len(actions)):
            if results[j] is None:
                results[j] = self._result(actions[j], "halted",
                                          error="lost leadership")
                self._count("AUTOPILOT_DEFERRALS", labels=("halted",))
        self._gauge("AUTOPILOT_QUEUE_DEPTH", 0)
        return results

    async def _pause_gate(self) -> str:
        """Park while the fleet pages. Returns "ok" or "defer"."""
        if self.paging is None or not await self.paging():
            self._gauge("AUTOPILOT_PAUSED", 0)
            return "ok"
        events.record("autopilot_pause")
        self._count("AUTOPILOT_PAUSES")
        self._gauge("AUTOPILOT_PAUSED", 1)
        t0 = time.monotonic()
        while await self.paging():
            if time.monotonic() - t0 > self.pause_max_s:
                self._gauge("AUTOPILOT_PAUSED", 0)
                return "defer"
            self.paused_s += _PAUSE_POLL_S
            await self._sleep(_PAUSE_POLL_S)
        self._gauge("AUTOPILOT_PAUSED", 0)
        return "ok"

    def _result(self, a: Action, status: str, error: str = "",
                target: str = "", seconds: float = 0.0) -> dict:
        return {"action": a.to_dict(), "status": status,
                "error": error, "target": target or a.target,
                "seconds": round(seconds, 3),
                "wall_ms": round(time.time() * 1000.0, 3)}

    async def _run_action(self, a: Action) -> dict:
        self.in_flight[a.key()] = a.to_dict()
        t0 = time.monotonic()
        try:
            if self.dryrun:
                events.record("autopilot_action", kind=a.kind,
                              vid=a.vid, target=a.target, dryrun=True,
                              reason=a.reason)
                self._count("AUTOPILOT_ACTIONS",
                            labels=(a.kind, "dryrun"))
                return self._result(a, "dryrun")
            # (the autopilot.execute chaos site fires inside the
            # injected node_post transport, so every dispatch below is
            # individually breakable)
            target, last = "", None
            policy = RetryPolicy(max_attempts=2, base_delay=0.2,
                                 total_timeout=900.0,
                                 sleep=self._sleep,
                                 name=f"autopilot.{a.kind}")
            done = False
            async for _ in policy.attempts():
                try:
                    target = await self._dispatch(a)
                    done = True
                    break
                except (aiohttp_errors() + (OSError, ActionError,
                                            asyncio.TimeoutError)) as e:
                    last = e
            if not done:
                raise last if last is not None \
                    else ActionError("retries exhausted")
            secs = time.monotonic() - t0
            events.record("autopilot_action", kind=a.kind, vid=a.vid,
                          target=target, reason=a.reason,
                          seconds=round(secs, 3))
            self._count("AUTOPILOT_ACTIONS", labels=(a.kind, "ok"))
            self._count("AUTOPILOT_REPAIR_BYTES", a.bytes_est)
            return self._result(a, "ok", target=target, seconds=secs)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — one failed repair must
            # not end the cycle; the failure is journaled and the next
            # cycle re-plans from fresh observation
            secs = time.monotonic() - t0
            glog.warning("autopilot %s vid=%d: %s: %s", a.kind, a.vid,
                         type(e).__name__, e)
            events.record("autopilot_action", kind=a.kind, vid=a.vid,
                          target=a.target, error=str(e)[:160],
                          reason=a.reason)
            self._count("AUTOPILOT_ACTIONS", labels=(a.kind, "error"))
            return self._result(a, "error", error=str(e)[:300],
                                seconds=secs)
        finally:
            self.in_flight.pop(a.key(), None)

    # ---- per-kind dispatch --------------------------------------------

    async def _dispatch(self, a: Action) -> str:
        if a.kind == KIND_REBUILD:
            return await self._rebuild(a)
        if a.kind == KIND_REPLICATE:
            return await self._replicate(a)
        if a.kind == KIND_VACUUM:
            return await self._vacuum(a)
        if a.kind == KIND_TIER:
            return await self._tier(a)
        raise ActionError(f"unknown action kind {a.kind!r}")

    async def _rebuild(self, a: Action) -> str:
        """Rebuild-to-target: one POST per attempt; ranked fallback
        targets absorb a refusing node (dead, wrong -workers
        partition, no space)."""
        sources = ",".join(f"{sid}:{url}" for sid, url in a.sources)
        last: Exception | None = None
        for target in (a.targets or (a.target,)):
            try:
                await self.node_post(
                    target, "/admin/ec/rebuild_shard",
                    {"volume": str(a.vid), "collection": a.collection,
                     "shards": ",".join(map(str, a.shards)),
                     "sources": sources}, timeout_s=600.0)
                return target
            except (aiohttp_errors() + (OSError, ActionError,
                                        asyncio.TimeoutError)) as e:
                last = e
        raise last if last is not None else ActionError("no target")

    async def _replicate(self, a: Action) -> str:
        last: Exception | None = None
        src = a.holders[0]
        for target in (a.targets or (a.target,)):
            try:
                await self.node_post(
                    target, "/admin/volume/copy",
                    {"volume": str(a.vid), "collection": a.collection,
                     "source": src}, timeout_s=600.0)
                return target
            except (aiohttp_errors() + (OSError, ActionError,
                                        asyncio.TimeoutError)) as e:
                last = e
        raise last if last is not None else ActionError("no target")

    async def _vacuum(self, a: Action) -> str:
        """compact -> commit on every holder, cleanup on failure — the
        shell volume.vacuum workflow, demand-driven. Each phase awaits
        EVERY holder (return_exceptions) before deciding: a bare
        gather would raise on the first failure while sibling
        compacts are still rewriting, and firing cleanup concurrently
        with an in-flight compact would delete its .cpd/.cpx out from
        under it."""
        vid = {"volume": str(a.vid)}

        async def phase(path: str, timeout_s: float) -> None:
            done = await asyncio.gather(*(
                self.node_post(u, path, vid, timeout_s=timeout_s)
                for u in a.holders), return_exceptions=True)
            for r in done:
                if isinstance(r, BaseException):
                    raise r
        try:
            await phase("/admin/vacuum/compact", 600.0)
            await phase("/admin/vacuum/commit", 600.0)
        except Exception:
            await asyncio.gather(*(
                self.node_post(u, "/admin/vacuum/cleanup", vid,
                               timeout_s=60.0) for u in a.holders),
                return_exceptions=True)
            raise
        return ",".join(a.holders)

    async def _tier(self, a: Action) -> str:
        for u in a.holders:
            await self.node_post(
                u, "/admin/tier/upload",
                {"volume": str(a.vid), "backend": a.target},
                timeout_s=600.0)
        return ",".join(a.holders)


def aiohttp_errors() -> tuple:
    """aiohttp's error tuple, import-deferred so pure-planner tests
    never pay for (or require) the HTTP stack."""
    import aiohttp
    return (aiohttp.ClientError,)
