"""Observe phase: build a frozen ClusterSnapshot from live evidence.

Four sources, all of which earlier PRs built as *reporting* surfaces
and the autopilot now consumes as *inputs*:

* the leader's in-process ``Topology`` (heartbeat-fed node/volume/EC
  registries — rack placement, free slots, per-volume deletion
  counters, liveness);
* every live holder's ``/debug/scrub`` — specifically the
  machine-readable per-cycle ``corrupt_windows`` rows (vid, window
  offset, localized shard ids) the scrubber emits since this PR,
  NOT the human-facing prose/corruption ring;
* every live holder's ``/debug/health`` verdict plus the master's own
  — any ``page`` anywhere parks the executor (repair traffic must
  never bury a foreground incident);
* the heartbeat ``remote`` bit on volume messages, so already-tiered
  volumes are never re-planned for tier_seal.

The observer is the only autopilot phase that touches the network; it
degrades gracefully (an unreachable holder contributes no scrub/health
evidence and is reported in ``errors``) and everything it returns is
immutable, so the planner downstream stays pure.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp

from ..security import tls
from ..storage.super_block import ReplicaPlacement
from ..util import failpoints, glog
from .plan import (ClusterSnapshot, CorruptionReport, EcVolumeState,
                   NodeState, VolumeState)

# concurrent per-node probes; a big fleet is walked in waves
_PROBE_FANOUT = 8


class Observer:
    """Builds snapshots for one MasterServer (leader-side only)."""

    def __init__(self, master, timeout_s: float = 10.0):
        self.master = master
        self.timeout_s = timeout_s

    # ---- HTTP probe helpers -------------------------------------------

    async def _get_json(self, url: str, path: str) -> dict:
        # chaos site: each observation probe is individually breakable
        # (a node whose evidence can't be read degrades, never wedges)
        await failpoints.fail("autopilot.observe")
        async with self.master._http.get(
                tls.url(url, path),
                timeout=aiohttp.ClientTimeout(
                    total=self.timeout_s)) as resp:
            if resp.status != 200:
                raise OSError(f"GET {url}{path}: http {resp.status}")
            return await resp.json()

    @staticmethod
    def _scrub_statuses(body: dict) -> "list[dict]":
        """Normalize a /debug/scrub GET body: a plain server answers
        {"scrub": {...}}, a -workers entry worker answers
        {"workers": {"0": {...}, ...}}."""
        if "scrub" in body:
            return [body["scrub"]]
        return [s for s in body.get("workers", {}).values()
                if isinstance(s, dict) and "state" in s]

    async def _probe_node(self, url: str,
                          corrupt: dict, errors: list) -> bool:
        """Scrub + health probe of one holder; returns its paging bit."""
        paging = False
        try:
            body = await self._get_json(url, "/debug/scrub")
            for st in self._scrub_statuses(body):
                last = st.get("last_cycle") or {}
                for row in last.get("corrupt_windows", ()):
                    key = (int(row["volume"]), int(row["offset"]))
                    corrupt[key] = CorruptionReport(
                        vid=int(row["volume"]),
                        offset=int(row["offset"]),
                        size=int(row.get("size", 0)),
                        shards=tuple(sorted(
                            int(s) for s in row.get("shards", ()))))
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError, KeyError) as e:
            errors.append({"node": url, "surface": "scrub",
                           "error": str(e)[:160]})
        try:
            h = await self._get_json(url, "/debug/health")
            paging = h.get("status") == "page"
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError) as e:
            errors.append({"node": url, "surface": "health",
                           "error": str(e)[:160]})
        return paging

    async def any_paging(self) -> bool:
        """Fresh fleet-wide page check (the executor's pause gate):
        every live holder's /debug/health plus the master's own."""
        urls = [n.url for n in self._alive_nodes()] + [self.master.url]
        sem = asyncio.Semaphore(_PROBE_FANOUT)

        async def one(u: str) -> bool:
            async with sem:
                try:
                    h = await self._get_json(u, "/debug/health")
                    return h.get("status") == "page"
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError, ValueError):
                    return False    # unreachable != paging
        return any(await asyncio.gather(*(one(u) for u in urls)))

    # ---- topology distillation ----------------------------------------

    def _alive_nodes(self) -> list:
        topo = self.master.topo
        now = time.time()
        limit = 3 * topo.pulse_seconds
        return [n for n in topo.all_nodes()
                if now - n.last_seen <= limit]

    async def snapshot(self) -> "tuple[ClusterSnapshot, list[dict]]":
        """One full observation pass -> (snapshot, probe errors)."""
        # chaos site: a broken observer must surface as a failed cycle
        # (state visible in /debug/autopilot), never a wedged loop
        await failpoints.fail("autopilot.observe")
        alive = {n.url: n for n in self._alive_nodes()}
        nodes = tuple(sorted(
            (NodeState(url=n.url,
                       data_center=(n.rack.data_center.id
                                    if n.rack and n.rack.data_center
                                    else ""),
                       rack=n.rack.id if n.rack else "",
                       free_slots=n.free_space())
             for n in alive.values()),
            key=lambda s: s.url))

        topo = self.master.topo
        volumes = []
        for vid, locs in sorted(topo.volume_locations.items()):
            live = sorted(n.url for n in locs.values()
                          if n.url in alive)
            if not live:
                continue            # no live holder: nothing to act from
            msg = None
            for n in sorted(locs.values(), key=lambda n: n.url):
                if n.url in alive and vid in n.volumes:
                    msg = n.volumes[vid]
                    break
            if msg is None:
                continue
            try:
                copies = ReplicaPlacement.from_byte(
                    msg.replica_placement).copy_count
            except ValueError:
                copies = 1
            volumes.append(VolumeState(
                vid=vid, collection=msg.collection, size=msg.size,
                deleted_bytes=msg.deleted_byte_count,
                read_only=msg.read_only,
                remote=getattr(msg, "remote", False),
                replica_count=copies, holders=tuple(live)))

        ec_volumes = []
        for vid, by_shard in sorted(topo.ec_shard_locations.items()):
            shards = []
            for sid, holders in sorted(by_shard.items()):
                live = tuple(sorted(n.url for n in holders
                                    if n.url in alive))
                if live:
                    shards.append((sid, live))
            if shards:
                ec_volumes.append(EcVolumeState(
                    vid=vid,
                    collection=topo.collections.get(vid, ""),
                    shards=tuple(shards)))

        # scrub + health fan-out over every live holder (+ the leader
        # itself for health); unreachable nodes degrade to "no
        # evidence", recorded in errors
        corrupt: dict[tuple, CorruptionReport] = {}
        errors: list[dict] = []
        sem = asyncio.Semaphore(_PROBE_FANOUT)

        async def probe(u: str) -> bool:
            async with sem:
                return await self._probe_node(u, corrupt, errors)

        paging_bits = list(await asyncio.gather(
            *(probe(u) for u in sorted(alive))))
        try:
            h = await self._get_json(self.master.url, "/debug/health")
            paging_bits.append(h.get("status") == "page")
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError) as e:
            glog.V(2).infof("autopilot: master health probe: %s", e)

        snap = ClusterSnapshot(
            nodes=nodes,
            volumes=tuple(volumes),
            ec_volumes=tuple(ec_volumes),
            corruptions=tuple(corrupt[k] for k in sorted(corrupt)),
            volume_size_limit=self.master.volume_size_limit,
            paging=any(paging_bits))
        return snap, errors
