"""Pure maintenance planner: observed cluster state -> ordered actions.

The plan phase of the autopilot's observe -> plan -> execute loop
(ROADMAP item 5 "close the operations loop"). Everything here is a
pure function over frozen dataclasses: identical snapshots produce
identical ordered plans (property-tested in tests/test_autopilot.py),
which is what makes `-autopilot.dryrun` an honest ledger of exactly
what live mode would do and lets every decision be journaled with a
machine-checkable `reason`.

Action families, in priority order (the Facebook warehouse study
1309.0186 makes repair traffic a first-class bandwidth consumer, and
2306.10528 frames single-shard loss as the dominant repair case —
so single-shard rebuilds outrank everything else):

* ``rebuild_shard``     — a declared EC shard is lost (holder died) or
  rotten (scrub localized corruption to it): regenerate it on a
  rack-aware target (`topology/layout.rank_repair_targets`) via the
  volume server's rebuild-to-target route.
* ``replicate_volume``  — a plain volume has fewer live replicas than
  its declared placement: copy from a surviving holder to a rack-aware
  target (`/admin/volume/copy`).
* ``vacuum_volume``     — deletion ratio past the garbage threshold:
  compact + commit on every holder (the master's manual/auto vacuum
  workflow, now demand-driven).
* ``tier_seal``         — a sealed (read-only, still-local) volume and
  a configured tier backend: ship the .dat to the remote tier
  (`/admin/tier/upload`, storage/volume_tier.py).

The planner never talks to the network and never mutates its input;
capacity- or evidence-limited decisions come back as typed
``Deferral`` rows so the journal can say *why* nothing was done.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from ..ec import gf
from ..topology.layout import rank_repair_targets

KIND_REBUILD = "rebuild_shard"
KIND_REPLICATE = "replicate_volume"
KIND_VACUUM = "vacuum_volume"
KIND_TIER = "tier_seal"

KINDS = (KIND_REBUILD, KIND_REPLICATE, KIND_VACUUM, KIND_TIER)


# ---- observed state (built by observe.py, consumed read-only) ----------


@dataclass(frozen=True)
class NodeState:
    """One live volume server (a -workers worker is its own node)."""

    url: str
    data_center: str = ""
    rack: str = ""
    free_slots: int = 0


@dataclass(frozen=True)
class VolumeState:
    """One plain volume with its live holder set."""

    vid: int
    collection: str = ""
    size: int = 0
    deleted_bytes: int = 0
    read_only: bool = False
    remote: bool = False            # .dat already on a tier backend
    replica_count: int = 1          # declared copies (placement + 1)
    holders: tuple = ()             # live holder urls, sorted


@dataclass(frozen=True)
class EcVolumeState:
    """One EC volume: (shard id, live holder urls) pairs, sorted."""

    vid: int
    collection: str = ""
    shards: tuple = ()              # ((sid, (url, ...)), ...)


@dataclass(frozen=True)
class CorruptionReport:
    """One corrupt stripe window from a holder's /debug/scrub report.
    `shards` carries the scrubber's localization verdict — empty means
    the rot could not be pinned to one shard (multi-shard rot or an
    ambiguous window) and the planner defers instead of guessing."""

    vid: int
    offset: int = 0
    size: int = 0
    shards: tuple = ()


@dataclass(frozen=True)
class ClusterSnapshot:
    """Everything the planner is allowed to know, frozen."""

    nodes: tuple = ()               # (NodeState, ...) sorted by url
    volumes: tuple = ()             # (VolumeState, ...) sorted by vid
    ec_volumes: tuple = ()          # (EcVolumeState, ...) sorted by vid
    corruptions: tuple = ()         # (CorruptionReport, ...)
    volume_size_limit: int = 0      # master's -volumeSizeLimitMB in bytes
    paging: bool = False            # any /debug/health verdict == page


@dataclass(frozen=True)
class PlannerConfig:
    garbage_threshold: float = 0.3
    tier_backend: str = ""          # empty disables tier_seal planning
    max_actions: int = 64


@dataclass(frozen=True)
class Action:
    """One typed repair decision, self-describing for the journal."""

    kind: str
    vid: int
    collection: str = ""
    priority: int = 9
    shards: tuple = ()              # shard ids to (re)build
    target: str = ""                # primary placement target
    targets: tuple = ()             # ranked fallbacks, target first
    sources: tuple = ()             # ((sid, holder_url), ...) gather map
    holders: tuple = ()             # current holders (vacuum/tier/copy src)
    bytes_est: int = 0              # conservative bytes the action moves
    reason: str = ""                # why this action was chosen

    def key(self) -> tuple:
        """Identity for dedup/cooldown across cycles."""
        return (self.kind, self.vid, self.shards, self.target)

    def to_dict(self) -> dict:
        d = asdict(self)
        for k in ("shards", "targets", "holders"):
            d[k] = list(d[k])
        d["sources"] = [list(s) for s in self.sources]
        return d


@dataclass(frozen=True)
class Deferral:
    """Why the planner chose NOT to act — first-class journal output."""

    vid: int
    kind: str
    reason: str

    def to_dict(self) -> dict:
        return asdict(self)


# ---- the planner -------------------------------------------------------


def plan(snap: ClusterSnapshot,
         cfg: PlannerConfig) -> "tuple[list[Action], list[Deferral]]":
    """Diff observed state against declared redundancy -> ordered plan.

    Deterministic and pure: every collection iterated in sorted order,
    every tie broken by (priority, vid, shards, target), no RNG, no
    clock, no I/O. The returned actions are already in execution order.
    """
    actions: list[Action] = []
    deferrals: list[Deferral] = []
    nodes = sorted(snap.nodes, key=lambda n: n.url)

    # corrupt windows grouped per vid: localized shard ids repair;
    # an UNLOCALIZED window poisons the whole vid — some unknown
    # survivor is corrupt, so ANY rebuild (of a localized-rotten OR a
    # lost shard) could regenerate from rotten rows and overwrite
    # good bytes with derived garbage. Defer the vid entirely.
    rotten: dict[int, set] = {}
    unlocalized: set = set()
    for rep in sorted(snap.corruptions,
                      key=lambda r: (r.vid, r.offset, r.shards)):
        if rep.shards:
            rotten.setdefault(rep.vid, set()).update(rep.shards)
        else:
            unlocalized.add(rep.vid)

    shard_bytes_est = max(1, snap.volume_size_limit // gf.DATA_SHARDS) \
        if snap.volume_size_limit else 1 << 20

    for ev in sorted(snap.ec_volumes, key=lambda e: e.vid):
        if ev.vid in unlocalized:
            deferrals.append(Deferral(ev.vid, KIND_REBUILD,
                                      "corruption-unlocalized"))
            continue
        actions_d, defer_d = _plan_ec_volume(
            ev, nodes, rotten.get(ev.vid, set()), shard_bytes_est)
        actions.extend(actions_d)
        deferrals.extend(defer_d)
    for vid in sorted(unlocalized):
        if not any(e.vid == vid for e in snap.ec_volumes):
            deferrals.append(Deferral(vid, KIND_REBUILD,
                                      "corruption-unlocalized"))

    for vs in sorted(snap.volumes, key=lambda v: v.vid):
        a, d = _plan_plain_volume(vs, nodes, cfg)
        actions.extend(a)
        deferrals.extend(d)

    actions.sort(key=lambda a: (a.priority, a.vid, a.shards, a.target))
    if len(actions) > cfg.max_actions:
        for a in actions[cfg.max_actions:]:
            deferrals.append(Deferral(a.vid, a.kind, "queue-full"))
        actions = actions[:cfg.max_actions]
    deferrals.sort(key=lambda d: (d.vid, d.kind, d.reason))
    return actions, deferrals


def _holder_map(ev: EcVolumeState) -> "dict[int, tuple]":
    return {sid: holders for sid, holders in ev.shards if holders}


def _plan_ec_volume(ev: EcVolumeState, nodes: list,
                    rotten_sids: set, shard_bytes_est: int
                    ) -> "tuple[list[Action], list[Deferral]]":
    held = _holder_map(ev)
    missing = sorted(sid for sid in range(gf.TOTAL_SHARDS)
                     if sid not in held)
    # a rotten shard whose holder died is just missing; only
    # still-hosted rotten shards get the in-place rebuild
    rot = sorted(sid for sid in rotten_sids if sid in held)
    if not missing and not rot:
        return [], []
    survivors = sorted(sid for sid in held if sid not in rotten_sids)
    if len(survivors) < gf.DATA_SHARDS:
        return [], [Deferral(ev.vid, KIND_REBUILD, "unrepairable")]
    # gather map: for every clean survivor shard, its first holder
    # (sorted — deterministic); the executor ships this to the target
    sources = tuple((sid, held[sid][0]) for sid in survivors)
    total_repairs = len(missing) + len(rot)
    prio = 0 if total_repairs == 1 else 1
    out: list[Action] = []
    defer: list[Deferral] = []

    # lost shards: rack-aware NEW placement, spread round-robin so a
    # multi-shard rebuild never re-concentrates redundancy on one node
    if missing:
        holder_urls = {u for hs in held.values() for u in hs}
        ranked = rank_repair_targets(nodes, holder_urls)
        if not ranked:
            # nowhere rack-aware to put them: fall back to the least
            # loaded existing holders rather than leaving redundancy
            # degraded (holding two shards beats holding data hostage)
            by_load: dict[str, int] = {}
            for hs in held.values():
                for u in hs:
                    by_load[u] = by_load.get(u, 0) + 1
            ranked = [u for u, _ in sorted(by_load.items(),
                                           key=lambda t: (t[1], t[0]))]
        if not ranked:
            defer.append(Deferral(ev.vid, KIND_REBUILD, "no-target"))
        else:
            per_target: dict[str, list] = {}
            for i, sid in enumerate(missing):
                per_target.setdefault(ranked[i % len(ranked)],
                                      []).append(sid)
            for target in sorted(per_target):
                sids = tuple(sorted(per_target[target]))
                fallbacks = tuple([target] + [u for u in ranked
                                              if u != target])
                out.append(Action(
                    kind=KIND_REBUILD, vid=ev.vid,
                    collection=ev.collection, priority=prio,
                    shards=sids, target=target, targets=fallbacks,
                    sources=sources,
                    bytes_est=gf.DATA_SHARDS * shard_bytes_est,
                    reason=f"{len(missing)} shard(s) lost, "
                           f"{len(held)}/{gf.TOTAL_SHARDS} hosted"))

    # rotten shards: rebuild IN PLACE on the current holder — the bad
    # copy is deleted there and regenerated from the clean survivors.
    # A shard with MULTIPLE holders defers: the scrub report cannot
    # say WHICH holder's copy is rotten, and regenerating the wrong
    # (clean) one would leave the rot serving forever.
    per_holder: dict[str, list] = {}
    for sid in rot:
        if len(held[sid]) > 1:
            defer.append(Deferral(ev.vid, KIND_REBUILD,
                                  "rot-multi-holder"))
            continue
        per_holder.setdefault(held[sid][0], []).append(sid)
    for target in sorted(per_holder):
        sids = tuple(sorted(per_holder[target]))
        out.append(Action(
            kind=KIND_REBUILD, vid=ev.vid, collection=ev.collection,
            priority=prio, shards=sids, target=target,
            targets=(target,), sources=sources,
            bytes_est=gf.DATA_SHARDS * shard_bytes_est,
            reason=f"scrub localized rot to shard(s) {list(sids)}"))
    return out, defer


def _plan_plain_volume(vs: VolumeState, nodes: list, cfg: PlannerConfig
                       ) -> "tuple[list[Action], list[Deferral]]":
    out: list[Action] = []
    defer: list[Deferral] = []
    if not vs.holders:
        return out, defer           # nothing to copy from — not plannable
    # under-replication: declared copies not met by live holders
    if len(vs.holders) < vs.replica_count and not vs.remote:
        ranked = rank_repair_targets(nodes, set(vs.holders))
        if not ranked:
            defer.append(Deferral(vs.vid, KIND_REPLICATE, "no-target"))
        else:
            need = vs.replica_count - len(vs.holders)
            for i in range(min(need, len(ranked))):
                out.append(Action(
                    kind=KIND_REPLICATE, vid=vs.vid,
                    collection=vs.collection, priority=2,
                    target=ranked[i],
                    targets=tuple(ranked[i:]),
                    holders=vs.holders, bytes_est=vs.size,
                    reason=f"{len(vs.holders)}/{vs.replica_count} "
                           f"replicas live"))
    # vacuum: deletion ratio past threshold (never a sealed/remote
    # volume — compaction rewrites the .dat, which a tiered volume no
    # longer owns locally)
    if (not vs.read_only and not vs.remote and vs.size > 0
            and vs.deleted_bytes / vs.size >= cfg.garbage_threshold):
        out.append(Action(
            kind=KIND_VACUUM, vid=vs.vid, collection=vs.collection,
            priority=3, holders=vs.holders,
            bytes_est=max(0, vs.size - vs.deleted_bytes)
            * len(vs.holders),
            reason=f"garbage ratio "
                   f"{vs.deleted_bytes / vs.size:.2f} >= "
                   f"{cfg.garbage_threshold:.2f}"))
    # cold tiering: sealed, still local, a tier backend is configured
    if (cfg.tier_backend and vs.read_only and not vs.remote
            and vs.size > 0):
        out.append(Action(
            kind=KIND_TIER, vid=vs.vid, collection=vs.collection,
            priority=4, holders=vs.holders,
            bytes_est=vs.size * len(vs.holders),
            reason=f"sealed volume, tier backend "
                   f"{cfg.tier_backend!r} configured",
            target=cfg.tier_backend))
    return out, defer
