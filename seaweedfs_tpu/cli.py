"""`weed`-style CLI: one entry point, subcommand per server/tool.

Reference: weed/weed.go:37-60 + weed/command/ (command registry,
command/command.go:10-30). Run as `python -m seaweedfs_tpu.cli <cmd>`.
"""

from __future__ import annotations

from .security import tls
from .security.guard import parse_white_list
from .util import tracing

import argparse
import asyncio
import json
import os
import sys
import time


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-master", default="127.0.0.1:9333",
                   help="master host:port")
    p.add_argument("-v", type=int, default=0, dest="verbosity",
                   help="glog verbose level (V(n) guards)")
    p.add_argument("-logdir", default="",
                   help="write per-severity rotated log files here")
    p.add_argument("-logtostderr", default=True,
                   type=lambda s: s.lower() not in ("false", "0", "no"),
                   help="also log to stderr (set false with -logdir for "
                        "file-only logging)")
    p.add_argument("-cpuprofile", default="",
                   help="write cProfile stats here on exit (under "
                        "-workers N each worker writes <path>.w<index> "
                        "so the dumps don't clobber each other)")
    p.add_argument("-memprofile", default="",
                   help="write tracemalloc top allocations here on exit "
                        "(suffixed .w<index> under -workers, like "
                        "-cpuprofile)")
    p.add_argument("-trace.sample", dest="trace_sample", type=float,
                   default=1.0,
                   help="distributed-tracing sample rate for requests "
                        "arriving WITHOUT a traceparent header (0 = "
                        "start no traces here; requests carrying an "
                        "upstream sampled traceparent are still joined "
                        "and recorded — set 0 fleet-wide to silence "
                        "tracing end to end)")
    p.add_argument("-trace.slowms", dest="trace_slowms", type=float,
                   default=0.0,
                   help="glog WARNING (with the trace id) for any entry "
                        "span slower than this many ms; 0 disables")
    p.add_argument("-trace.ring", dest="trace_ring", type=int,
                   default=2048,
                   help="finished spans kept in the per-process "
                        "/debug/traces ring buffer")
    p.add_argument("-profile.hz", dest="profile_hz", type=float,
                   default=0.0,
                   help="continuous sampling profiler rate in Hz "
                        "(stats/profiler.py): folds every thread's "
                        "stack into /debug/profile, attributed to the "
                        "active trace tier; 0 (default) disables the "
                        "always-on sampler — /debug/profile?seconds=N "
                        "still records on-demand windows")
    p.add_argument("-timeline.interval", dest="timeline_interval",
                   type=float, default=10.0,
                   help="metrics-timeline snapshot cadence in seconds "
                        "(/debug/timeline flight recorder); 0 disables "
                        "the recorder on this daemon")
    p.add_argument("-timeline.ring", dest="timeline_ring", type=int,
                   default=360,
                   help="timeline windows kept per process (default "
                        "360 = 1h of 10s windows)")
    p.add_argument("-slo", action="append", default=[],
                   help="declarative latency objective evaluated over "
                        "the timeline with fast/slow burn-rate windows "
                        "and served at /debug/health, e.g. "
                        "'volume.read:p99<50ms@99.9' or per-tenant "
                        "'s3.get/paying:p99<200ms@99' (repeatable)")
    p.add_argument("-qos.tenant", dest="qos_tenant", action="append",
                   default=[],
                   help="tenant QoS class 'key:weight:rps[:burst]' — "
                        "key is the SigV4 access key / JWT sub (or "
                        "'default' for unclassified traffic), weight "
                        "sets the weighted-fair share and shed "
                        "priority, rps the token-bucket rate (0 = "
                        "unlimited); repeatable, arms per-tenant "
                        "admission on the s3/filer/webdav tiers and "
                        "/debug/qos")
    p.add_argument("-qos.shed.lagms", dest="qos_shed_lagms",
                   type=float, default=0.0,
                   help="arm priority load shedding when the sampled "
                        "event-loop lag crosses this many ms (lowest "
                        "weight class shed first; 0 disables)")
    p.add_argument("-qos.shed.waitms", dest="qos_shed_waitms",
                   type=float, default=0.0,
                   help="arm shedding on executor queue wait above "
                        "this many ms (same ladder as -qos.shed.lagms)")
    p.add_argument("-qos.mbps", dest="qos_mbps", type=float,
                   default=0.0,
                   help="cluster foreground byte budget in MiB/s for "
                        "the bandwidth arbiter: background consumers "
                        "(scrub, autopilot) yield toward -qos.floor as "
                        "foreground traffic approaches it; the leader "
                        "master publishes it to volume nodes through "
                        "heartbeats (0 disables arbitration)")
    p.add_argument("-qos.floor", dest="qos_floor", type=float,
                   default=0.25,
                   help="starvation-proof fraction of a background "
                        "consumer's base rate the arbiter always "
                        "grants, whatever the foreground pressure")


def _add_workers(p: argparse.ArgumentParser) -> None:
    p.add_argument("-workers", type=int, default=1,
                   help="process-per-core data plane: N worker "
                        "processes share the port via SO_REUSEPORT "
                        "(volume: ownership partitioned vid %% N; "
                        "master: worker 0 is the full master, the rest "
                        "are /dir/assign accelerators)")
    # internal: set by the supervisor when re-executing itself as a
    # worker; never passed by operators
    p.add_argument("-workerIndex", type=int, default=-1,
                   help=argparse.SUPPRESS)
    p.add_argument("-workerStateDir", default="",
                   help=argparse.SUPPRESS)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="weed-tpu",
                                 description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("master", help="start a master server")
    _add_common(m)
    _add_workers(m)
    m.add_argument("-port", type=int, default=9333)
    m.add_argument("-volumeSizeLimitMB", type=int, default=30_000)
    m.add_argument("-defaultReplication", default="000")
    m.add_argument("-pulseSeconds", type=float, default=5.0)
    m.add_argument("-jwtKey", default="")
    m.add_argument("-peers", default="",
                   help="comma-separated peer masters host:port "
                        "(enables the raft quorum: leader election, "
                        "replicated fid/volume-id allocation, follower "
                        "307-redirect-to-leader)")
    m.add_argument("-raft.timeout", dest="raft_timeout",
                   default="1.0,2.0",
                   help="election timeout range seconds 'min,max' "
                        "(randomized per follower; failover completes "
                        "within ~2 timeouts of a leader death)")
    m.add_argument("-raft.pulse", dest="raft_pulse", type=float,
                   default=0.3,
                   help="leader AppendEntries heartbeat cadence "
                        "seconds (the lease window derives from the "
                        "election timeout, not this)")
    m.add_argument("-metricsGateway", default="",
                   help="prometheus push-gateway host:port")
    m.add_argument("-sequencer", default=None,
                   help="file-id allocator: memory | file:<path> | "
                        "etcd:<host:port> (default: master.toml "
                        "[master.sequencer], else memory)")
    m.add_argument("-mdir", default="",
                   help="master metadata dir (persists election "
                        "term/vote across restarts)")
    m.add_argument("-garbageThreshold", type=float, default=0.3,
                   help="auto-vacuum when a volume's garbage ratio "
                        "exceeds this")
    m.add_argument("-maintenanceIntervalS", type=float, default=900.0,
                   help="auto-vacuum cadence seconds; 0 disables")
    m.add_argument("-whiteList", default="",
                   help="comma-separated IPs/CIDRs allowed to use the "
                        "API; empty = no limit (guard.go). Heartbeating "
                        "volume servers are auto-admitted; with -peers, "
                        "follower control routes 307-redirect so the "
                        "CLIENT IP is judged on the leader — only "
                        "/submit still proxies from peer master IPs")
    m.add_argument("-volumePreallocate", action="store_true",
                   help="preallocate disk space for grown volumes")
    m.add_argument("-autopilot.interval", dest="autopilot_interval",
                   type=float, default=0.0,
                   help="autopilot maintenance-plane cycle cadence "
                        "seconds (leader-only observe->plan->execute: "
                        "auto-rebuild lost/rotten EC shards, "
                        "re-replicate, vacuum, cold-tier); 0 disables "
                        "the loop (POST /debug/autopilot?run=1 still "
                        "forces one cycle)")
    m.add_argument("-autopilot.mbps", dest="autopilot_mbps",
                   type=float, default=16.0,
                   help="cluster-wide repair-bandwidth token bucket "
                        "(MB/s of estimated repair bytes); <=0 "
                        "unpaced")
    m.add_argument("-autopilot.dryrun", dest="autopilot_dryrun",
                   action="store_true",
                   help="plan, journal and report the exact action "
                        "ledger live mode would execute — but execute "
                        "nothing")
    m.add_argument("-autopilot.concurrency",
                   dest="autopilot_concurrency", type=int, default=2,
                   help="maintenance actions in flight at once")
    m.add_argument("-autopilot.tier", dest="autopilot_tier",
                   default="",
                   help="tier backend id (e.g. s3.default) for "
                        "tier_seal actions: sealed still-local "
                        "volumes are shipped to it; empty disables "
                        "cold-tiering actions")
    m.add_argument("-introspect.deadline", dest="introspect_deadline",
                   type=float, default=3.0,
                   help="per-node deadline seconds for the "
                        "/debug/cluster/* fan-out: a member that "
                        "doesn't answer within it degrades to a "
                        "missing_node row instead of stalling the "
                        "assembled view")

    v = sub.add_parser("volume", help="start a volume server")
    _add_common(v)
    _add_workers(v)
    v.add_argument("-port", type=int, default=8080)
    v.add_argument("-dir", default="./data", help="comma-separated dirs")
    v.add_argument("-max", default="8", help="comma-separated max volumes")
    v.add_argument("-dataCenter", default="")
    v.add_argument("-rack", default="")
    v.add_argument("-pulseSeconds", type=float, default=5.0)
    v.add_argument("-compactionMBps", type=int, default=0,
                   help="vacuum copy rate limit, MB/s (0 = unthrottled)")
    v.add_argument("-index", default="auto",
                   choices=["auto", "memory", "compact", "disk"],
                   help="needle map kind (reference -index=memory|leveldb;"
                        " disk = sqlite-backed, near-zero RAM)")
    v.add_argument("-jwtKey", default="")
    v.add_argument("-tierS3Endpoint", default="",
                   help="S3-compatible endpoint for volume.tier.upload "
                        "(configures backend id s3.default)")
    v.add_argument("-tierS3Bucket", default="volume-tier")
    v.add_argument("-tierMmapDir", default="",
                   help="directory (tmpfs/ramdisk for an in-memory tier) "
                        "for volume.tier.upload -backend mmap.default")
    v.add_argument("-ecBackend", default="auto",
                   choices=("auto", "tpu", "cpu"),
                   help="erasure-coding engine (the reference-noted "
                        "-ec.backend switch): auto = tpu when attached")
    v.add_argument("-publicUrl", default="",
                   help="publicly accessible address advertised to "
                        "clients (host:port)")
    v.add_argument("-whiteList", default="",
                   help="comma-separated IPs/CIDRs with needle-write "
                        "permission; empty = no limit. With security."
                        "toml mTLS the /admin mesh is cert-protected; "
                        "without it /admin mutations fall under this "
                        "list too (whitelist the master and peers)")
    v.add_argument("-cache.mem", dest="cache_mem", type=int, default=32,
                   help="total MB for volume-side read caches, split "
                        "3/4 hot-needle + 1/4 EC reconstruction "
                        "(strictly invalidated on write/delete/vacuum); "
                        "0 disables all volume-side read caching")
    v.add_argument("-batch.max", dest="batch_max", type=int, default=256,
                   help="most fids one /batch multi-needle GET may "
                        "carry (the unified wire's pipelined read)")
    v.add_argument("-groupcommit.ms", dest="groupcommit_ms", type=float,
                   default=0.0,
                   help="extra window the group-commit leader lingers "
                        "to deepen write batches; 0 = natural batching "
                        "(coalesce exactly when writers contend, zero "
                        "added latency for a lone writer)")
    v.add_argument("-fsync", action="store_true",
                   help="fsync every group-committed append before "
                        "acking writers (default keeps the historical "
                        "flush-only durability point)")
    v.add_argument("-scrub.interval", dest="scrub_interval", type=float,
                   default=0.0,
                   help="seconds between background EC parity-scrub "
                        "cycles; 0 disables the paced scrubber "
                        "(POST /debug/scrub?run=1 still forces one)")
    v.add_argument("-scrub.mbps", dest="scrub_mbps", type=float,
                   default=8.0,
                   help="token-bucket byte budget for scrub reads, "
                        "MiB/s — sustained scrub I/O never exceeds "
                        "this; 0 = unpaced")
    v.add_argument("-scrub.pausems", dest="scrub_pause_ms", type=float,
                   default=50.0,
                   help="park the scrubber while any foreground "
                        "request in the last 2s ran longer than this "
                        "many ms; 0 never pauses")
    # default None -> ec/batch.py DEFAULT_BATCH_WINDOWS (8), resolved
    # in VolumeServer: importing the engine (numpy) here would tax
    # EVERY CLI command's startup for one volume-only constant
    v.add_argument("-scrub.batch", dest="scrub_batch", type=int,
                   default=None,
                   help="stripe windows verified per scrub GF-transform "
                        "dispatch (default 8, the stripe-batch engine's "
                        "width, clamped so one block stays inside the "
                        "resident-byte budget); the byte budget and "
                        "foreground pause still gate every block; 1 "
                        "restores the per-window shape")
    v.add_argument("-ec.smallrecover", dest="ec_smallrecover", type=int,
                   default=1 << 20,
                   help="EC recover transforms smaller than this many "
                        "bytes run on the host CPU encoder instead of "
                        "the device (dispatch-latency crossover; "
                        "tools/bench_ec.py --mode bakeoff prints the "
                        "measured value so this default stays honest)")

    f = sub.add_parser("filer", help="start a filer server")
    _add_common(f)
    f.add_argument("-port", type=int, default=8888)
    f.add_argument("-store", default="sqlite",
                   help="filer store: memory|sqlite|leveldb|leveldb2|sql "
                        "(+redis/mysql/postgres/etcd/cassandra when "
                        "drivers are installed)")
    f.add_argument("-dbPath", default="./filer.db")
    f.add_argument("-chunkSizeMB", type=int, default=32)
    f.add_argument("-collection", default="")
    f.add_argument("-replication", default="")
    f.add_argument("-notify", default="",
                   help="publish meta changes: file:<path> | sqlite:<path> "
                        "| log")
    f.add_argument("-dataCenter", default="",
                   help="prefer volumes in this data center for writes")
    f.add_argument("-redirectOnRead", action="store_true",
                   help="redirect single-chunk GETs to the volume server "
                        "instead of proxying")
    f.add_argument("-disableDirListing", action="store_true")
    f.add_argument("-dirListLimit", type=int, default=100_000,
                   help="cap on directory listing page size")
    f.add_argument("-cache.mem", dest="cache_mem", type=int, default=64,
                   help="MB of memory for the chunk read cache "
                        "(0 disables)")
    f.add_argument("-cache.dir", dest="cache_dir", default="",
                   help="directory for the mmap-backed disk cache tier "
                        "(empty = memory-only)")
    f.add_argument("-shard.id", dest="shard_id", type=int, default=0,
                   help="this filer's shard id in a sharded metadata "
                        "plane (0-based)")
    f.add_argument("-shard.of", dest="shard_of", type=int, default=1,
                   help="total filer shards; >1 enables prefix sharding "
                        "against the master's raft-committed shard map")
    f.add_argument("-shard.peers", dest="shard_peers", default="",
                   help="comma list of filer host:port addresses indexed "
                        "by shard id (fallback when the committed map "
                        "has not learned an owner yet)")
    f.add_argument("-shard.splitMbps", dest="shard_split_mbps",
                   type=float, default=8.0,
                   help="token-bucket pacing for shard split/move "
                        "migration batches (adopted by the -qos.mbps "
                        "arbiter when present)")

    fc = sub.add_parser("filer.copy",
                        help="parallel-upload local files/trees to a filer")
    fc.add_argument("paths", nargs="+",
                    help="local files or directories, then the target "
                         "http://filer:port/dir/ URL last")
    fc.add_argument("-concurrency", type=int, default=8)
    fc.add_argument("-include", default="",
                    help="only copy names matching this glob (e.g. *.txt)")
    fc.add_argument("-collection", default="")
    fc.add_argument("-replication", default="")
    fc.add_argument("-ttl", default="",
                    help="time to live, e.g. 1m, 1h, 1d")

    fr = sub.add_parser("filer.replicate",
                        help="replay filer meta events into a sink "
                             "(flags fall back to replication.toml)")
    fr.add_argument("-notify", default="",
                    help="subscription input: file:<path> | sqlite:<path> "
                         "| kafka:<hosts>/<topic>[@offsets] | "
                         "sqs:<region>/<queue> | pubsub:<project>/<topic>")
    fr.add_argument("-sourceMaster", default="",
                    help="source cluster master host:port")
    fr.add_argument("-sourceDir", default="",
                    help="replicate only this subtree")
    fr.add_argument("-sink", default="",
                    help="filer:<filerHost:port>@<targetMaster> | "
                         "s3:<endpointUrl>/<bucket> | local:<dir>")
    fr.add_argument("-sinkDir", default="")
    fr.add_argument("-progress", default="")
    fr.add_argument("-once", action="store_true",
                    help="process the backlog and exit")

    s3p = sub.add_parser("s3", help="start an S3 gateway")
    _add_common(s3p)
    s3p.add_argument("-port", type=int, default=8333)
    s3p.add_argument("-store", default="sqlite")
    s3p.add_argument("-dbPath", default="./s3filer.db")
    s3p.add_argument("-accessKey", action="append", default=[],
                     help="require SigV4 auth with this access key "
                          "(repeatable — pair each with a -secretKey "
                          "in the same order for multi-tenant "
                          "credentials; empty = anonymous)")
    s3p.add_argument("-secretKey", action="append", default=[])
    s3p.add_argument("-domainName", default="",
                     help="enable virtual-host-style requests "
                          "(Host: bucket.<domainName>)")
    s3p.add_argument("-cache.mem", dest="cache_mem", type=int, default=64,
                     help="MB of memory for the chunk read cache "
                          "(0 disables)")
    s3p.add_argument("-cache.dir", dest="cache_dir", default="",
                     help="directory for the mmap-backed disk cache tier")
    s3p.add_argument("-shard.id", dest="shard_id", type=int, default=0,
                     help="this gateway's filer shard id in a sharded "
                          "gateway fleet")
    s3p.add_argument("-shard.of", dest="shard_of", type=int, default=1,
                     help="total gateway shards; >1 enables 307 "
                          "routing of foreign buckets to siblings")
    s3p.add_argument("-shard.peers", dest="shard_peers", default="",
                     help="comma list of sibling gateway host:port "
                          "addresses indexed by shard id")

    wd = sub.add_parser("webdav", help="start a WebDAV gateway")
    _add_common(wd)
    wd.add_argument("-port", type=int, default=7333)
    wd.add_argument("-store", default="sqlite",
                    help="filer store: memory|sqlite")
    wd.add_argument("-dbPath", default="./webdav.db")
    wd.add_argument("-collection", default="")
    wd.add_argument("-replication", default="")
    wd.add_argument("-chunkSizeMB", type=int, default=16)
    wd.add_argument("-cache.mem", dest="cache_mem", type=int, default=64,
                    help="MB of memory for the chunk read cache "
                         "(0 disables)")
    wd.add_argument("-cache.dir", dest="cache_dir", default="",
                    help="directory for the mmap-backed disk cache tier")
    wd.add_argument("-shard.id", dest="shard_id", type=int, default=0,
                    help="this gateway's filer shard id in a sharded "
                         "gateway fleet")
    wd.add_argument("-shard.of", dest="shard_of", type=int, default=1,
                    help="total gateway shards; >1 enables 307 "
                         "routing of foreign paths to siblings")
    wd.add_argument("-shard.peers", dest="shard_peers", default="",
                    help="comma list of sibling gateway host:port "
                         "addresses indexed by shard id")

    srv = sub.add_parser("server",
                         help="combined master+volume+filer+s3 in one process")
    _add_common(srv)
    srv.add_argument("-dir", default="./data")
    srv.add_argument("-masterPort", type=int, default=9333)
    srv.add_argument("-volumePort", type=int, default=8080)
    srv.add_argument("-filerPort", type=int, default=8888)
    srv.add_argument("-s3Port", type=int, default=8333)
    srv.add_argument("-s3", action="store_true")
    srv.add_argument("-filer", action="store_true")
    srv.add_argument("-jwtKey", default="")

    up = sub.add_parser("upload", help="upload files via assign+PUT")
    _add_common(up)
    up.add_argument("files", nargs="*", default=[])
    up.add_argument("-collection", default="")
    up.add_argument("-replication", default="")
    up.add_argument("-ttl", default="")
    up.add_argument("-dataCenter", default="")
    up.add_argument("-dir", dest="updir", default="",
                    help="upload this folder recursively (upload.go:35)")
    up.add_argument("-include", default="",
                    help="glob of names to upload, with -dir (e.g. *.pdf)")
    up.add_argument("-maxMB", type=int, default=0,
                    help="split files larger than this into a chunk "
                         "manifest (0 = never split)")

    dl = sub.add_parser("download", help="download a fid")
    _add_common(dl)
    dl.add_argument("fid")
    dl.add_argument("-o", dest="output", default="")

    sh = sub.add_parser("shell", help="admin shell (interactive or -c)")
    _add_common(sh)
    sh.add_argument("-c", dest="command", default="",
                    help="run one command and exit, e.g. 'ec.encode "
                         "-collection x'")

    bm = sub.add_parser("benchmark", help="write/read throughput benchmark")
    _add_common(bm)
    bm.add_argument("-n", type=int, default=1024)
    bm.add_argument("-size", type=int, default=1024)
    bm.add_argument("-c", dest="concurrency", type=int, default=16)
    bm.add_argument("-collection", default="benchmark")
    bm.add_argument("-replication", default="000")
    bm.add_argument("-write", default="true", choices=("true", "false"),
                    help="enable the write phase")
    bm.add_argument("-read", default="true", choices=("true", "false"),
                    help="enable the read phase")
    bm.add_argument("-deletePercent", type=int, default=0,
                    help="percent of writes immediately deleted again")
    bm.add_argument("-list", dest="idList", default="",
                    help="file of uploaded fids (written after the write "
                         "phase; read phase loads it when -write=false)")
    bm.add_argument("-readSequentially", nargs="?", const="true",
                    default="false", choices=("true", "false"),
                    help="read fids in list order instead of shuffled")
    bm.add_argument("-readMode", default="",
                    choices=("", "shuffle", "sequential", "zipf",
                             "batch"),
                    help="read-order distribution; zipf = repeated "
                         "hot-key reads (the cache-effectiveness "
                         "workload; overrides -readSequentially); "
                         "batch = shuffled order over multi-needle "
                         "/batch GETs")
    bm.add_argument("-readN", type=int, default=0,
                    help="total read requests (0 = one per fid); with "
                         "-readMode zipf the same hot fids repeat")
    bm.add_argument("-zipfS", type=float, default=1.1,
                    help="zipf exponent for -readMode zipf")
    bm.add_argument("-batchSize", type=int, default=0,
                    help="reads per multi-needle /batch request; >0 "
                         "batches ANY -readMode's order (-readMode "
                         "batch implies 32); reports req/s and "
                         "needles/s")
    bm.add_argument("-pipeline", type=int, default=0,
                    help="in-flight reads multiplexed per persistent "
                         "binary frame connection (util/frame.py); >0 "
                         "pipelines ANY -readMode's order depth-N over "
                         "one frame socket per server per client "
                         "(channel failures fall back to HTTP; a "
                         "missing needle is fatal, like single GETs); "
                         "mutually exclusive with -batchSize")

    bk = sub.add_parser("backup", help="incrementally back up one volume "
                                       "from a volume server to a local dir")
    bk.add_argument("-dir", default=".")
    bk.add_argument("-volumeId", type=int, required=True)
    bk.add_argument("-collection", default="")
    bk.add_argument("-server", required=True,
                    help="source volume server host:port")

    fx = sub.add_parser("fix", help="rebuild .idx by scanning .dat")
    fx.add_argument("-dir", default=".")
    fx.add_argument("-volumeId", type=int, required=True)
    fx.add_argument("-collection", default="")

    ex = sub.add_parser("export", help="list/dump needles in a volume")
    ex.add_argument("-dir", default=".")
    ex.add_argument("-volumeId", type=int, required=True)
    ex.add_argument("-collection", default="")
    ex.add_argument("-o", dest="output", default="",
                    help="write file contents to this .tar instead of "
                         "printing the JSON listing")
    ex.add_argument("-fileNameFormat", default="{name}",
                    help="tar member naming: {name} {id} {mime}")
    ex.add_argument("-newer", default="",
                    help="only needles modified after this "
                         "YYYY-MM-DDThh:mm:ss")
    ex.add_argument("-pattern", default="",
                    help="only file names matching this glob")
    ex.add_argument("-limit", type=int, default=0,
                    help="stop after this many entries (0 = all)")

    co = sub.add_parser("compact", help="offline-compact one volume")
    co.add_argument("-dir", default=".")
    co.add_argument("-volumeId", type=int, required=True)
    co.add_argument("-collection", default="")

    sc = sub.add_parser("scaffold", help="print example config TOML")
    sc.add_argument("-config", default="security",
                    choices=["security", "master", "filer",
                             "notification", "replication"])

    mt = sub.add_parser("mount", help="mount the filer as a FUSE "
                                      "filesystem (requires fusepy)")
    _add_common(mt)
    mt.add_argument("-dir", required=True, help="mount point")
    mt.add_argument("-collection", default="")
    mt.add_argument("-replication", default="")
    mt.add_argument("-ttl", default="")
    mt.add_argument("-chunkSizeLimitMB", type=int, default=4)
    mt.add_argument("-filerStore", default="memory",
                    help="embedded filer store backing the mount")

    sub.add_parser("version", help="print version")
    bench = sub.add_parser("bench-ec", help="TPU EC kernel benchmark "
                                            "(bench.py)")
    return ap


# ---------------------------------------------------------------------------


def _find_config_toml(name: str) -> tuple[str, dict] | None:
    """viper-style discovery of <name>.toml in ./, ~/.seaweedfs,
    /etc/seaweedfs (util/config.go:28-45); returns (path, parsed)."""
    from .util.toml import tomllib
    if tomllib is None:
        # no TOML parser on this Python (tomllib is 3.11+): config
        # discovery is disabled rather than every command crashing
        return None
    for d in (".", os.path.expanduser("~/.seaweedfs"), "/etc/seaweedfs"):
        path = os.path.join(d, f"{name}.toml")
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    return path, tomllib.load(f)
            except tomllib.TOMLDecodeError as e:
                # a broken discovered config must fail loud and clean,
                # never a raw traceback from a command that may not even
                # need the file
                raise SystemExit(f"{path}: {e}")
    return None


def _discover_notification_queue():
    """Discovered notification.toml (the scaffold's [notification.*]
    enabled sections, configuration.go:24-58). Used by every command
    that embeds a filer when -notify is not given; returns the one
    enabled queue or None. Config errors exit cleanly like the -notify
    flag path does."""
    found = _find_config_toml("notification")
    if found is None:
        return None
    path, cfg = found
    from .notification.queues import load_configuration
    try:
        q = load_configuration(cfg.get("notification"))
    except (ValueError, RuntimeError, KeyError) as e:
        raise SystemExit(f"{path}: {e}")
    if q is not None:
        from .util import glog
        glog.info("notification queue %s from %s", q.name, path)
    return q


def _attach_discovered_queue(filer) -> None:
    q = _discover_notification_queue()
    if q is not None:
        from .notification.queues import attach_to_filer
        attach_to_filer(filer, q)


def _load_master_toml() -> dict:
    """Discovered master.toml: [master.maintenance] scripts +
    sleep_minutes and [master.sequencer] type (scaffold.go:337-371
    semantics)."""
    found = _find_config_toml("master")
    if found is None:
        return {}
    path, cfg = found
    out = {}
    maint = cfg.get("master", {}).get("maintenance", {})
    if maint.get("scripts"):
        out["admin_scripts"] = [
            ln.strip() for ln in maint["scripts"].splitlines()
            if ln.strip() and not ln.strip().startswith("#")]
    if "sleep_minutes" in maint:
        out["admin_scripts_interval_s"] = \
            float(maint["sleep_minutes"]) * 60
    seq = cfg.get("master", {}).get("sequencer", {})
    if seq.get("type") and seq["type"] != "memory":
        val = seq["type"]
        out["sequencer"] = (val if ":" in val
                            else f"{val}:{seq.get('path', '')}")
    from .util import glog
    glog.info("master config loaded from %s", path)
    return out


async def _serve_until_interrupt(*servers) -> None:
    """Run until SIGINT/SIGTERM/SIGHUP, then stop servers in order.

    The graceful path (reference: weed/util/signal_handling.go:19-44 +
    httpdown) — stop() commits needle maps / closes stores, and the
    normal return lets atexit fire, which is what dumps
    -cpuprofile/-memprofile output (util/pprof.py)."""
    from .util import glog
    from .util.signals import wait_for_interrupt
    num = await wait_for_interrupt()
    glog.V(1).infof("signal %s: shutting down %d server(s)",
                    num, len(servers))
    for srv in servers:
        try:
            await srv.stop()
        except Exception as e:  # noqa: BLE001 — best-effort drain
            glog.warning("shutdown of %s: %s", type(srv).__name__, e)


def _worker_state_dir(args, kind: str) -> str:
    if args.workerStateDir:
        return args.workerStateDir
    if kind == "volume":
        return os.path.join(args.dir.split(",")[0], ".workers")
    return os.path.join(args.mdir or ".", ".workers")


async def _run_worker_supervisor(args, kind: str) -> None:
    """Parent of `-workers N`: mint the launch token, spawn the worker
    processes (this same command line + -workerIndex i), restart
    crashed ones. No socket lives here — see server/workers.py."""
    import secrets
    from .server.workers import (Supervisor, WORKER_TOKEN_ENV,
                                 fresh_state_dir)
    if args.port == 0:
        raise SystemExit(f"{kind} -workers needs an explicit -port "
                         f"(the workers share it via SO_REUSEPORT)")
    state_dir = await tracing.run_in_executor(
        fresh_state_dir, _worker_state_dir(args, kind))
    env = dict(os.environ)
    env[WORKER_TOKEN_ENV] = env.get(WORKER_TOKEN_ENV) \
        or secrets.token_hex(16)
    raw = list(getattr(args, "_raw_argv", None) or sys.argv[1:])

    def build_argv(index: int) -> list[str]:
        return ([sys.executable, "-m", "seaweedfs_tpu.cli"] + raw
                + ["-workerIndex", str(index),
                   "-workerStateDir", state_dir])

    sup = Supervisor(build_argv, args.workers, env=env)
    await sup.start()
    print(f"{kind} supervisor: {args.workers} workers sharing port "
          f"{args.port} (state: {state_dir})")
    from .util.signals import wait_for_interrupt
    await wait_for_interrupt()
    await sup.stop()


_BACKGROUND_TASKS: set = set()  # strong refs: loop tasks are weakly held


def _watch_parent() -> None:
    """Workers exit when the supervisor disappears (reparented to
    init), so a SIGKILLed supervisor never leaks port-holding
    orphans."""
    ppid = os.getppid()

    async def watch() -> None:
        while os.getppid() == ppid:
            await asyncio.sleep(1.0)
        os._exit(0)

    task = asyncio.get_running_loop().create_task(watch())
    _BACKGROUND_TASKS.add(task)
    task.add_done_callback(_BACKGROUND_TASKS.discard)


def _make_worker_ctx(args, kind: str):
    from .server.workers import WorkerContext
    return WorkerContext(args.workerIndex, args.workers, args.port,
                         _worker_state_dir(args, kind))


def _start_recorder(disk_paths: "list[str] | None" = None):
    """Start the flight-recorder sampling loop for a daemon process
    (no-op handle when -timeline.interval 0). The caller cancels the
    returned handle on shutdown."""
    from .stats import timeline
    return timeline.start_recorder(disk_paths=disk_paths)


async def _run_master(args) -> None:
    from .master.server import MasterServer
    if args.workers > 1 and args.workerIndex < 0:
        await _run_worker_supervisor(args, "master")
        return
    if args.workerIndex > 0:
        # assign accelerator: shares the port, leases ids, proxies cold
        from .server.workers import AssignAccelerator
        _watch_parent()
        acc = AssignAccelerator(
            args.ip, args.port, _make_worker_ctx(args, "master"),
            white_list=parse_white_list(args.whiteList),
            jwt_key=args.jwtKey,
            default_replication=args.defaultReplication)
        await acc.start()
        rec = _start_recorder()
        print(f"master assign worker {args.workerIndex} on {acc.url}")
        try:
            await _serve_until_interrupt(acc)
        finally:
            if rec is not None:
                rec.cancel()
        return
    worker_ctx = None
    if args.workerIndex == 0:
        _watch_parent()
        worker_ctx = _make_worker_ctx(args, "master")
    from .stats import introspect
    introspect.init(args.introspect_deadline)
    toml_cfg = await tracing.run_in_executor(_load_master_toml)
    try:
        lo, _, hi = args.raft_timeout.partition(",")
        election_timeout = (float(lo), float(hi or lo))
    except ValueError:
        raise SystemExit(f"-raft.timeout {args.raft_timeout!r}: "
                         f"want 'min,max' seconds") from None
    # ctor makedirs -mdir; keep daemon construction off the loop —
    # under -workers respawn this loop is already serving
    m = await tracing.run_in_executor(lambda: MasterServer(
        ip=args.ip, port=args.port,
        volume_size_limit_mb=args.volumeSizeLimitMB,
        default_replication=args.defaultReplication,
        pulse_seconds=args.pulseSeconds, jwt_key=args.jwtKey,
        peers=[p.strip() for p in args.peers.split(",")
               if p.strip()],
        election_timeout=election_timeout,
        election_pulse=args.raft_pulse,
        # explicit CLI flag beats discovered config (None =
        # flag not given, so even an explicit `-sequencer
        # memory` overrides a master.toml sequencer)
        sequencer=(args.sequencer if args.sequencer is not None
                   else toml_cfg.get("sequencer", "memory")),
        meta_dir=args.mdir,
        garbage_threshold=args.garbageThreshold,
        maintenance_interval_s=args.maintenanceIntervalS,
        admin_scripts=toml_cfg.get("admin_scripts"),
        admin_scripts_interval_s=toml_cfg.get(
            "admin_scripts_interval_s", 17 * 60.0),
        white_list=parse_white_list(args.whiteList),
        volume_preallocate=args.volumePreallocate,
        autopilot_interval_s=args.autopilot_interval,
        autopilot_mbps=args.autopilot_mbps,
        autopilot_dryrun=args.autopilot_dryrun,
        autopilot_concurrency=args.autopilot_concurrency,
        autopilot_tier_backend=args.autopilot_tier,
        worker_ctx=worker_ctx))
    await m.start()
    push_task = None
    if args.metricsGateway:
        from .stats.metrics import push_loop
        push_task = asyncio.create_task(
            push_loop(args.metricsGateway, "master"))
    rec = _start_recorder()
    print(f"master listening on {m.url}")
    try:
        await _serve_until_interrupt(m)
    finally:
        if push_task is not None:
            push_task.cancel()
        if rec is not None:
            rec.cancel()


async def _run_volume(args) -> None:
    from .server.volume_server import VolumeServer
    from .storage.store import Store
    if args.workers > 1 and args.workerIndex < 0:
        await _run_worker_supervisor(args, "volume")
        return
    worker_ctx = None
    if args.workerIndex >= 0:
        _watch_parent()
        worker_ctx = _make_worker_ctx(args, "volume")
    dirs = args.dir.split(",")
    maxes = [int(x) for x in args.max.split(",")]
    if len(maxes) == 1:
        maxes = maxes * len(dirs)
    # the flag is authoritative: an explicit `-ecBackend auto` clears an
    # inherited pin from the parent environment
    os.environ["SWTPU_EC_BACKEND"] = args.ecBackend
    tier_cfg = {}
    if args.tierS3Endpoint:
        tier_cfg["s3"] = {"default": {"endpoint": args.tierS3Endpoint,
                                      "bucket": args.tierS3Bucket}}
    if args.tierMmapDir:
        tier_cfg["mmap"] = {"default": {"dir": args.tierMmapDir}}
    if tier_cfg:
        from .storage.backend import load_backends
        load_backends(tier_cfg)
    # Store's ctor makedirs + scans every volume file — a worker
    # respawned into a live fleet must not stall its fresh loop
    store = await tracing.run_in_executor(lambda: Store(
        dirs, max_volume_counts=maxes,
        compaction_bytes_per_second=args.compactionMBps
        * 1024 * 1024,
        index_type=args.index,
        partition=(None if worker_ctx is None else
                   (worker_ctx.index, worker_ctx.total)),
        needle_cache_bytes=args.cache_mem * 1024 * 1024,
        group_commit_window=args.groupcommit_ms / 1000.0,
        fsync=args.fsync,
        ec_small_recover_bytes=args.ec_smallrecover))
    vs = VolumeServer(store, args.master, ip=args.ip, port=args.port,
                      data_center=args.dataCenter, rack=args.rack,
                      pulse_seconds=args.pulseSeconds, jwt_key=args.jwtKey,
                      white_list=parse_white_list(args.whiteList),
                      public_url=args.publicUrl,
                      worker_ctx=worker_ctx,
                      batch_max=args.batch_max,
                      scrub_mbps=args.scrub_mbps,
                      scrub_interval=args.scrub_interval,
                      scrub_pause_ms=args.scrub_pause_ms,
                      scrub_batch=args.scrub_batch)
    await vs.start()
    rec = _start_recorder(disk_paths=dirs)
    if worker_ctx is not None:
        print(f"volume worker {worker_ctx.index}/{worker_ctx.total}: "
              f"public {args.ip}:{worker_ctx.public_port}, "
              f"private {vs.url}, dirs={dirs}")
    else:
        print(f"volume server listening on {vs.url}, dirs={dirs}")
    try:
        await _serve_until_interrupt(vs)
    finally:
        if rec is not None:
            rec.cancel()


def _store_kwargs(store: str, db_path: str) -> dict:
    if store in ("sqlite", "sql"):
        return {"path": db_path}
    if store in ("leveldb", "leveldb2"):
        return {"dir": db_path}
    return {}


async def _run_filer(args) -> None:
    from .filer.filer import Filer
    from .server.filer_server import FilerServer
    kwargs = _store_kwargs(args.store, args.dbPath)
    filer = Filer(args.store, **kwargs)
    if args.notify:
        from .notification.queues import attach_to_filer
        attach_to_filer(filer, await tracing.run_in_executor(
            _make_queue, args.notify))
    else:
        await tracing.run_in_executor(_attach_discovered_queue, filer)
    fs = FilerServer(filer, args.master,
                     ip=args.ip, port=args.port,
                     chunk_size=args.chunkSizeMB * 1024 * 1024,
                     collection=args.collection,
                     replication=args.replication,
                     data_center=args.dataCenter,
                     redirect_on_read=args.redirectOnRead,
                     disable_dir_listing=args.disableDirListing,
                     dir_list_limit=args.dirListLimit,
                     cache_mem_bytes=args.cache_mem * 1024 * 1024,
                     cache_dir=args.cache_dir,
                     shard_id=args.shard_id, shard_of=args.shard_of,
                     shard_peers={i: p.strip() for i, p in
                                  enumerate(args.shard_peers.split(","))
                                  if p.strip()},
                     shard_split_mbps=args.shard_split_mbps)
    await fs.start()
    rec = _start_recorder()
    shard_note = (f", shard {args.shard_id}/{args.shard_of}"
                  if args.shard_of > 1 else "")
    print(f"filer listening on {fs.url} (store={args.store}"
          f"{shard_note})")
    try:
        await _serve_until_interrupt(fs)
    finally:
        if rec is not None:
            rec.cancel()


def _make_queue(spec: str):
    from .notification.queues import queue_from_spec
    try:
        return queue_from_spec(spec)
    except ValueError as e:
        raise SystemExit(str(e))


def _make_subscription(spec: str):
    """filer.replicate consumption input: the file/sqlite queues plus the
    broker subscribers (replication/sub.py, driver-gated like the
    publishers). Broker specs:
      kafka:<host1,host2>/<topic>[@offset_file]
      sqs:<region>/<queue_name>
      pubsub:<project_id>/<topic>
    """
    kind, _, rest = spec.partition(":")
    if kind in ("file", "sqlite"):  # NOT "log": it records, can't replay
        return _make_queue(spec)
    from .replication import sub as rsub
    if kind == "kafka":
        hosts, _, rest2 = rest.partition("/")
        topic, _, offset_file = rest2.partition("@")
        q = rsub.KafkaInput()
        q.initialize({"hosts": hosts.split(","), "topic": topic,
                      "offset_file": offset_file or None})
        return q
    if kind == "sqs":
        region, _, name = rest.partition("/")
        q = rsub.SqsInput()
        q.initialize({"region": region, "sqs_queue_name": name})
        return q
    if kind == "pubsub":
        project, _, topic = rest.partition("/")
        q = rsub.GooglePubSubInput()
        q.initialize({"project_id": project, "topic": topic})
        return q
    raise SystemExit(f"bad -notify spec {spec!r}; use file:<path> | "
                     f"sqlite:<path> | kafka:<hosts>/<topic>[@offsets] | "
                     f"sqs:<region>/<queue> | pubsub:<project>/<topic>")


def _make_sink(spec: str, sink_dir: str):
    from .replication.sink import FilerSink, LocalDirSink, S3Sink
    kind, _, rest = spec.partition(":")
    if kind == "filer":
        target, _, master = rest.partition("@")
        if not (target and master):
            raise SystemExit(
                "bad -sink: filer:<filerHost:port>@<targetMaster>")
        return FilerSink(target, master, directory=sink_dir)
    if kind == "s3":
        endpoint, _, bucket = rest.rpartition("/")
        if not (endpoint and bucket):
            raise SystemExit("bad -sink: s3:<endpointUrl>/<bucket>")
        return S3Sink(endpoint, bucket, directory=sink_dir)
    if kind == "local":
        return LocalDirSink(rest)
    raise SystemExit(f"unknown sink kind {kind!r}")


async def _run_filer_copy(args) -> None:
    """Parallel tree upload to the filer HTTP surface
    (reference: weed filer.copy, command/filer_copy.go)."""
    import fnmatch

    import aiohttp

    *sources, dest = args.paths
    if not dest.startswith("http"):
        raise SystemExit("last argument must be the target "
                         "http://filer:port/dir/ URL")
    if not dest.endswith("/"):
        dest += "/"

    jobs: list[tuple[str, str]] = []  # (local path, remote rel path)
    for src in sources:
        if os.path.isdir(src):
            base = os.path.basename(os.path.abspath(src))
            walked = await tracing.run_in_executor(
                _walk_upload_files, src, args.include)
            for full in walked:
                rel = os.path.join(base, os.path.relpath(full, src))
                jobs.append((full, rel))
        elif os.path.isfile(src):
            if not args.include or fnmatch.fnmatch(
                    os.path.basename(src), args.include):
                jobs.append((src, os.path.basename(src)))
        else:
            raise SystemExit(f"no such file or directory: {src}")

    import urllib.parse

    sem = asyncio.Semaphore(args.concurrency)
    copied = errors = 0
    attr_params = {k: v for k, v in (
        ("collection", args.collection),
        ("replication", args.replication),
        ("ttl", args.ttl)) if v}

    async with tls.make_session() as http:
        async def upload(local: str, rel: str) -> bool:
            async with sem:
                try:
                    # hand the file object to FormData so aiohttp streams
                    # it instead of holding whole files in memory; open
                    # and close leave the loop — N concurrent uploads
                    # share it
                    f = await tracing.run_in_executor(open, local, "rb")
                    try:
                        form = aiohttp.FormData()
                        form.add_field("file", f,
                                       filename=os.path.basename(rel))
                        target = dest + urllib.parse.quote(
                            rel.replace(os.sep, "/"))
                        async with http.post(target, data=form,
                                             params=attr_params) as resp:
                            if resp.status not in (200, 201):
                                print(f"copy {local}: http {resp.status} "
                                      f"{await resp.text()}")
                                return False
                    finally:
                        await tracing.run_in_executor(f.close)
                except (OSError, aiohttp.ClientError,
                        asyncio.TimeoutError) as e:
                    print(f"copy {local}: {e}")
                    return False
            return True

        results = await asyncio.gather(
            *(upload(l, r) for l, r in jobs))
        copied = sum(results)
        errors = len(results) - copied
    print(f"copied {copied} files to {dest}"
          + (f", {errors} errors" if errors else ""))
    if errors:
        raise SystemExit(1)


async def _run_filer_replicate(args) -> None:
    from .replication.replicator import Replicator
    from .replication.runner import replicate_from_queue
    from .replication.source import FilerSource
    # flags win; replication.toml [replication] fills whatever is absent
    found = await tracing.run_in_executor(
        _find_config_toml, "replication")
    cfg = found[1].get("replication", {}) if found else {}
    notify = args.notify or cfg.get("notify", "")
    source_master = args.sourceMaster or cfg.get("sourceMaster", "")
    source_dir = args.sourceDir or cfg.get("sourceDir", "/")
    sink_spec = args.sink or cfg.get("sink", "")
    sink_dir = args.sinkDir or cfg.get("sinkDir", "/")
    progress = args.progress or cfg.get("progress",
                                        "./replicate.progress")
    missing = [f for f, v in (("-notify", notify),
                              ("-sourceMaster", source_master),
                              ("-sink", sink_spec)) if not v]
    if missing:
        raise SystemExit(
            f"filer.replicate needs {', '.join(missing)} (flags or "
            f"replication.toml [replication] keys)")
    queue = await tracing.run_in_executor(_make_subscription, notify)
    sink = _make_sink(sink_spec, sink_dir)
    async with FilerSource(source_master, source_dir) as src:
        await sink.start()
        try:
            n = await replicate_from_queue(
                queue, Replicator(src, sink), progress,
                once=args.once)
            if args.once:
                print(f"replicated {n} events")
        finally:
            await sink.close()
            closer = getattr(queue, "close", None)
            if closer is not None:
                closer()


def _gateway_router(args):
    """-shard.of > 1: build the GatewayRouter for a sharded S3/WebDAV
    fleet (one gateway per filer shard, siblings from -shard.peers)."""
    if getattr(args, "shard_of", 1) <= 1:
        return None
    from .filer.shard import GatewayRouter
    peers = {i: p.strip() for i, p in
             enumerate(args.shard_peers.split(",")) if p.strip()}
    return GatewayRouter(args.shard_id, args.master, peers)


async def _run_s3(args) -> None:
    from .filer.filer import Filer
    from .s3.gateway import S3Gateway
    kwargs = _store_kwargs(args.store, args.dbPath)
    if len(args.accessKey) != len(args.secretKey):
        raise SystemExit("-accessKey and -secretKey must be paired "
                         "(one -secretKey per -accessKey, same order)")
    identities = (dict(zip(args.accessKey, args.secretKey))
                  if args.accessKey else None)
    filer = Filer(args.store, **kwargs)
    await tracing.run_in_executor(_attach_discovered_queue, filer)
    s3 = S3Gateway(filer, args.master,
                   ip=args.ip, port=args.port, identities=identities,
                   domain_name=args.domainName,
                   cache_mem_bytes=args.cache_mem * 1024 * 1024,
                   cache_dir=args.cache_dir,
                   shard_router=_gateway_router(args))
    await s3.start()
    rec = _start_recorder()
    print(f"s3 gateway listening on {s3.url}")
    try:
        await _serve_until_interrupt(s3)
    finally:
        if rec is not None:
            rec.cancel()


async def _run_webdav(args) -> None:
    from .filer.filer import Filer
    from .server.webdav_server import WebDavServer
    kwargs = _store_kwargs(args.store, args.dbPath)
    filer = Filer(args.store, **kwargs)
    await tracing.run_in_executor(_attach_discovered_queue, filer)
    # ctor builds the disk chunk-cache tier (makedirs)
    wd = await tracing.run_in_executor(lambda: WebDavServer(
        filer, args.master,
        ip=args.ip, port=args.port,
        collection=args.collection,
        replication=args.replication,
        chunk_size=args.chunkSizeMB * 1024 * 1024,
        cache_mem_bytes=args.cache_mem * 1024 * 1024,
        cache_dir=args.cache_dir,
        shard_router=_gateway_router(args)))
    await wd.start()
    rec = _start_recorder()
    print(f"webdav listening on {wd.url} (store={args.store})")
    try:
        await _serve_until_interrupt(wd)
    finally:
        if rec is not None:
            rec.cancel()


async def _run_server(args) -> None:
    """`weed server` combined launcher (command/server.go:103+)."""
    from .filer.filer import Filer
    from .master.server import MasterServer
    from .s3.gateway import S3Gateway
    from .server.filer_server import FilerServer
    from .server.volume_server import VolumeServer
    from .storage.store import Store

    m = await tracing.run_in_executor(lambda: MasterServer(
        ip=args.ip, port=args.masterPort, jwt_key=args.jwtKey))
    await m.start()
    # combined mode gets the standalone daemons' default cache budgets
    store = await tracing.run_in_executor(
        lambda: Store([args.dir], needle_cache_bytes=32 << 20))
    vs = VolumeServer(store, m.url, ip=args.ip, port=args.volumePort,
                      jwt_key=args.jwtKey)
    await vs.start()
    await vs.heartbeat_once()
    parts = [f"master={m.url}", f"volume={vs.url}"]
    filer_srv = None
    s3 = None
    if args.filer or args.s3:
        combined_filer = Filer("sqlite",
                               path=os.path.join(args.dir, "filer.db"))
        await tracing.run_in_executor(
            _attach_discovered_queue, combined_filer)
        filer_srv = FilerServer(
            combined_filer, m.url, ip=args.ip, port=args.filerPort,
            cache_mem_bytes=64 << 20)
        await filer_srv.start()
        parts.append(f"filer={filer_srv.url}")
    if args.s3:
        s3 = S3Gateway(filer_srv.filer, m.url, ip=args.ip, port=args.s3Port)
        await s3.start()
        parts.append(f"s3={s3.url}")
    print("server up: " + " ".join(parts))
    rec = _start_recorder(disk_paths=[args.dir])
    # data plane drains before the control plane disappears
    try:
        await _serve_until_interrupt(*[srv for srv in (s3, filer_srv, vs, m)
                                       if srv is not None])
    finally:
        if rec is not None:
            rec.cancel()


def _walk_upload_files(dir_path: str, include: str) -> list[str]:
    """Recursive -dir traversal filtered by the -include glob (shared by
    upload and filer.copy; upload.go:35-36)."""
    import fnmatch
    if not os.path.isdir(dir_path):
        raise SystemExit(f"no such directory: {dir_path}")
    out = []
    for root, _, names in os.walk(dir_path):
        for name in sorted(names):
            if include and not fnmatch.fnmatch(name, include):
                continue
            out.append(os.path.join(root, name))
    return out


def _read_file(path: str) -> bytes:
    """Sync whole-file read, for executor round-trips off the loop."""
    with open(path, "rb") as f:
        return f.read()


def _write_file(path: str, data: bytes) -> None:
    """Sync whole-file write, for executor round-trips off the loop."""
    with open(path, "wb") as f:
        f.write(data)


async def _run_upload(args) -> None:
    from .util.client import WeedClient
    max_mb = getattr(args, "maxMB", 0) or 0
    files = list(args.files)
    if args.updir:
        files.extend(await tracing.run_in_executor(
            _walk_upload_files, args.updir, args.include))
    if not files:
        raise SystemExit("upload: no input files (pass paths or -dir)")
    async with WeedClient(args.master) as c:
        out = []
        for path in files:
            data = await tracing.run_in_executor(_read_file, path)
            if max_mb > 0 and len(data) > max_mb * 1024 * 1024:
                # auto-split into a chunk manifest (submit.go:112-199)
                from .util.chunked import upload_in_chunks
                fid, cm = await upload_in_chunks(
                    c, data, max_mb, name=os.path.basename(path),
                    collection=args.collection,
                    replication=args.replication, ttl=args.ttl,
                    data_center=args.dataCenter)
                out.append({"fileName": os.path.basename(path),
                            "fid": fid, "size": len(data),
                            "chunks": len(cm.chunks),
                            "fileUrl": await c.lookup_file_id(fid)})
                continue
            fid = await c.upload_data(data, collection=args.collection,
                                      replication=args.replication,
                                      ttl=args.ttl,
                                      data_center=args.dataCenter)
            out.append({"fileName": os.path.basename(path), "fid": fid,
                        "size": len(data),
                        "fileUrl": await c.lookup_file_id(fid)})
        print(json.dumps(out, indent=2))


async def _run_download(args) -> None:
    from .util.client import WeedClient
    async with WeedClient(args.master) as c:
        data = await c.read(args.fid)
    out = args.output or args.fid.replace(",", "_")
    await tracing.run_in_executor(_write_file, out, data)
    print(f"wrote {len(data)} bytes to {out}")


async def _run_shell(args) -> None:
    from .shell.env import CommandEnv
    from .shell.runner import dispatch, run_command, HELP
    if args.command:
        await run_command(args.master, args.command)
        return
    print("seaweedfs_tpu shell; 'help' for commands, 'exit' to quit")
    # one env for the whole session so fs.cd working-directory state
    # carries across commands (shell_liner.go keeps one CommandEnv)
    async with CommandEnv(args.master) as env:
        while True:
            try:
                line = await tracing.run_in_executor(input, "> ")
            except (EOFError, KeyboardInterrupt):
                break
            line = line.strip()
            if line in ("exit", "quit"):
                break
            if line == "help":
                print(HELP)
                continue
            if line:
                try:
                    res = await dispatch(env, line)
                    if res is not None:
                        print(json.dumps(res, indent=2, default=str))
                except Exception as e:
                    print(f"error: {e}")


class _RawConn:
    """One persistent raw HTTP/1.1 connection for the benchmark loop.

    The reference's benchmark client is a lean Go net/http loop
    (command/benchmark.go); a full aiohttp ClientSession here would
    measure the client's own parser, not the servers, on a single core."""

    __slots__ = ("r", "w", "hostport", "_hdr")

    @classmethod
    async def open(cls, hostport: str) -> "_RawConn":
        host, _, port = hostport.rpartition(":")
        c = cls.__new__(cls)
        c.hostport = hostport
        c.r, c.w = await asyncio.open_connection(
            host or "127.0.0.1", int(port), ssl=tls.client_ctx())
        c._hdr = f"\r\nHost: {hostport}\r\n".encode()
        return c

    async def request(self, method: str, path: str, body: bytes = b"",
                      ctype: str = "") -> tuple[int, bytes]:
        head = method.encode() + b" " + path.encode() + b" HTTP/1.1" \
            + self._hdr
        if body or method in ("POST", "PUT"):
            head += b"Content-Length: " + str(len(body)).encode() + b"\r\n"
        if ctype:
            head += b"Content-Type: " + ctype.encode() + b"\r\n"
        self.w.write(head + b"\r\n" + body)
        await self.w.drain()
        hdr = await self.r.readuntil(b"\r\n\r\n")
        status = int(hdr[9:12])
        i = hdr.lower().find(b"content-length:")
        cl = 0
        if i >= 0:
            cl = int(hdr[i + 15:hdr.index(b"\r\n", i)])
        data = await self.r.readexactly(cl) if cl else b""
        return status, data

    def close(self) -> None:
        try:
            self.w.close()
        except OSError:
            pass  # already-dead socket: nothing left to release


async def _run_benchmark(args) -> None:
    """weed benchmark analog (command/benchmark.go): concurrent small-file
    writes + reads with latency percentiles, over per-worker persistent
    raw connections (see _RawConn)."""
    import random

    rng = random.Random(0)
    payload = bytes(rng.getrandbits(8) for _ in range(args.size))
    write_lat: list[float] = []
    read_lat: list[float] = []
    fids: list[str] = []
    deletes = 0
    do_write = args.write == "true"
    do_read = args.read == "true"
    if not do_write:
        if not args.idList:
            raise SystemExit("-write=false needs -list <fid file> "
                             "from an earlier write run")
        raw = await tracing.run_in_executor(_read_file, args.idList)
        fids = [ln.strip() for ln in raw.decode().splitlines()
                if ln.strip()]

    master = args.master.split(",")[0]
    assign_q = "/dir/assign"
    qs = []
    if args.collection:
        qs.append(f"collection={args.collection}")
    if args.replication:
        qs.append(f"replication={args.replication}")
    if qs:
        assign_q += "?" + "&".join(qs)
    vol_locs: dict[str, str] = {}       # vid -> host:port (lookup cache)
    read_bytes = 0
    wi = ri = 0                          # shared cursors (single loop)
    # -batchSize / -readMode batch: reads ride multi-needle /batch GETs
    batch_size = args.batchSize or (32 if args.readMode == "batch"
                                    else 0)
    pipeline = args.pipeline
    if pipeline and batch_size:
        raise SystemExit("-pipeline and -batchSize are mutually "
                         "exclusive read transports")
    read_reqs = 0                        # wire requests (batch != needle)
    needles_read = 0
    frame_fallbacks = 0                  # pipeline reads downgraded to HTTP

    async def lookup(mconn: _RawConn, vid: str) -> str:
        url = vol_locs.get(vid)
        if url is None:
            st, body = await mconn.request(
                "GET", f"/dir/lookup?volumeId={vid}")
            if st != 200:
                raise RuntimeError(f"lookup {vid}: {st}")
            url = json.loads(body)["locations"][0]["url"]
            vol_locs[vid] = url
        return url

    async def worker(phase: str, order: list[str]) -> None:
        nonlocal deletes, read_bytes, wi, ri, read_reqs, needles_read
        nonlocal frame_fallbacks
        mconn = await _RawConn.open(master)
        vconns: dict[str, _RawConn] = {}
        fchannels: dict[str, object] = {}

        async def vconn(hostport: str) -> _RawConn:
            c = vconns.get(hostport)
            if c is None:
                c = vconns[hostport] = await _RawConn.open(hostport)
            return c

        def fchannel(hostport: str):
            ch = fchannels.get(hostport)
            if ch is None:
                from .util.frame import FrameChannel
                ch = fchannels[hostport] = FrameChannel(
                    target=hostport, ssl=tls.client_ctx())
            return ch

        try:
            while True:
                if phase == "write":
                    if wi >= args.n:
                        return
                    wi += 1
                    t0 = time.perf_counter()
                    st, body = await mconn.request("GET", assign_q)
                    if st != 200:
                        raise RuntimeError(f"assign: {body[:200]!r}")
                    a = json.loads(body)
                    fid = a["fid"]
                    vc = await vconn(a["url"])
                    path = "/" + fid
                    auth = a.get("auth", "")
                    if auth:
                        # JWT rides as a query param the server accepts
                        path += "?jwt=" + auth
                    st, body = await vc.request("POST", path, payload)
                    if st not in (200, 201):
                        raise RuntimeError(f"upload {fid}: {st} "
                                           f"{body[:200]!r}")
                    # sample BEFORE any delete: the write percentiles
                    # must measure writes, not write+delete round trips
                    write_lat.append(time.perf_counter() - t0)
                    # random sampling like the reference (rand.Intn(100)):
                    # a modulo scheme front-loads deletes and skews the
                    # rate whenever n is not a multiple of 100
                    if args.deletePercent > 0 and \
                            rng.randrange(100) < args.deletePercent:
                        await vc.request("DELETE", "/" + fid)
                        deletes += 1
                    else:
                        fids.append(fid)
                elif pipeline:
                    if ri >= len(order):
                        return
                    group = order[ri:ri + pipeline]
                    ri += len(group)
                    by_server: dict[str, list[str]] = {}
                    for fid in group:
                        by_server.setdefault(
                            await lookup(mconn, fid.split(",")[0]),
                            []).append(fid)
                    from .util.frame import FrameChannelError
                    for server, fids_here in by_server.items():
                        ch = fchannel(server)
                        failed: list[str] = []

                        async def one(fid: str) -> None:
                            nonlocal read_bytes, needles_read
                            nonlocal read_reqs
                            t0 = time.perf_counter()
                            try:
                                st, _, data = await ch.request(
                                    "GET", "/" + fid)
                            except (FrameChannelError, OSError):
                                failed.append(fid)
                                return
                            read_lat.append(time.perf_counter() - t0)
                            if st != 200:
                                raise RuntimeError(
                                    f"pipelined read {fid}: {st}")
                            read_reqs += 1
                            needles_read += 1
                            read_bytes += len(data)

                        # depth-`pipeline` window: every request is in
                        # flight on ONE multiplexed frame connection
                        await asyncio.gather(*(one(f)
                                               for f in fids_here))
                        # channel-level failures ride HTTP, serially on
                        # this worker's keep-alive conn (rare path)
                        for fid in failed:
                            frame_fallbacks += 1
                            vc = await vconn(server)
                            t0 = time.perf_counter()
                            st, data = await vc.request("GET", "/" + fid)
                            if st != 200:
                                raise RuntimeError(f"read {fid}: {st}")
                            read_lat.append(time.perf_counter() - t0)
                            read_reqs += 1
                            needles_read += 1
                            read_bytes += len(data)
                elif batch_size:
                    if ri >= len(order):
                        return
                    group = order[ri:ri + batch_size]
                    ri += len(group)
                    # one /batch request per holding server (single
                    # server in this harness, but correct regardless)
                    by_server: dict[str, list[str]] = {}
                    for fid in group:
                        by_server.setdefault(
                            await lookup(mconn, fid.split(",")[0]),
                            []).append(fid)
                    from .util.batchframe import parse_all
                    for server, fids_here in by_server.items():
                        vc = await vconn(server)
                        t0 = time.perf_counter()
                        st, data = await vc.request(
                            "GET", "/batch?fids=" + ",".join(fids_here))
                        read_lat.append(time.perf_counter() - t0)
                        if st != 200:
                            raise RuntimeError(f"batch read: {st} "
                                               f"{data[:200]!r}")
                        read_reqs += 1
                        for meta, body in parse_all(data):
                            if meta.get("status") != 200:
                                raise RuntimeError(
                                    f"batch row {meta.get('fid')}: "
                                    f"{meta.get('status')}")
                            needles_read += 1
                            read_bytes += len(body)
                else:
                    if ri >= len(order):
                        return
                    fid = order[ri]
                    ri += 1
                    t0 = time.perf_counter()
                    vc = await vconn(
                        await lookup(mconn, fid.split(",")[0]))
                    st, data = await vc.request("GET", "/" + fid)
                    if st != 200:
                        raise RuntimeError(f"read {fid}: {st}")
                    read_lat.append(time.perf_counter() - t0)
                    read_bytes += len(data)
                    read_reqs += 1
                    needles_read += 1
        finally:
            mconn.close()
            for c in vconns.values():
                c.close()
            for ch in fchannels.values():
                await ch.close()

    wdt = 0.0
    if do_write:
        t0 = time.perf_counter()
        await asyncio.gather(*(worker("write", [])
                               for _ in range(args.concurrency)))
        wdt = time.perf_counter() - t0
        if args.idList:
            await tracing.run_in_executor(
                _write_file, args.idList,
                ("\n".join(fids) + "\n").encode())

    rdt = 0.0
    n_reads = 0
    if do_read and fids:
        mode = args.readMode or ("sequential"
                                 if args.readSequentially == "true"
                                 else "shuffle")
        if mode == "zipf":
            # zipf over a shuffled ranking: rank r drawn with weight
            # 1/r^s, so a small hot set dominates — the classic
            # read-mostly object-store mix the caches target
            ranked = list(fids)
            rng.shuffle(ranked)
            weights = [1.0 / (r + 1) ** args.zipfS
                       for r in range(len(ranked))]
            order = rng.choices(ranked, weights=weights,
                                k=args.readN or len(ranked))
        elif mode == "sequential":
            order = list(fids)
        else:
            order = list(fids)
            rng.shuffle(order)
        n_reads = len(order)
        t0 = time.perf_counter()
        await asyncio.gather(*(worker("read", order)
                               for _ in range(args.concurrency)))
        rdt = time.perf_counter() - t0

    def pct(xs, p):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(p / 100 * len(xs)))] * 1e3

    if do_write:
        print(f"write: {args.n / wdt:.1f} req/s, "
              f"{args.n * args.size / wdt / 1024:.1f} KB/s"
              + (f" ({deletes} deletes)" if deletes else ""))
        print(f"  latency ms p50/p95/p99/max: {pct(write_lat, 50):.1f}/"
              f"{pct(write_lat, 95):.1f}/{pct(write_lat, 99):.1f}/"
              f"{max(write_lat) * 1e3:.1f}")
    if do_read and fids:
        # measured bytes, not -size: a -write=false run may read fids
        # written with a different size
        print(f"read:  {read_reqs / rdt:.1f} req/s, "
              f"{read_bytes / rdt / 1024:.1f} KB/s")
        if batch_size:
            # the amortization headline: needles served per second vs
            # wire round trips spent serving them
            print(f"  needles/s: {needles_read / rdt:.1f} "
                  f"(batch={batch_size}, {needles_read} needles over "
                  f"{read_reqs} requests)")
        if pipeline:
            # the overlap headline: depth-N multiplexed frames on one
            # socket per server, no round-trip wait per needle
            print(f"  needles/s: {needles_read / rdt:.1f} "
                  f"(pipeline={pipeline} over frames, "
                  f"{frame_fallbacks} HTTP fallbacks)")
        print(f"  latency ms p50/p95/p99/max: {pct(read_lat, 50):.1f}/"
              f"{pct(read_lat, 95):.1f}/{pct(read_lat, 99):.1f}/"
              f"{max(read_lat) * 1e3:.1f}")


async def _run_backup(args) -> None:
    """Incremental volume backup (command/backup.go): pull the tail of a
    remote volume newer than the local copy; falls back to a full fetch
    when compaction revisions diverge or local is ahead."""
    import aiohttp

    from .storage import volume_backup as vb
    from .storage.volume import Volume

    async with tls.make_session(
            timeout=aiohttp.ClientTimeout(total=300)) as http:
        async with http.get(
                tls.url(args.server, "/admin/volume/status"),
                params={"volume": str(args.volumeId)}) as resp:
            if resp.status != 200:
                print(f"volume {args.volumeId} not found on {args.server}")
                sys.exit(1)
            st = await resp.json()
        from .storage import types as t
        from .storage.super_block import ReplicaPlacement
        collection = args.collection or st.get("collection", "")
        # Volume's ctor replays .idx/.dat metadata from disk
        v = await tracing.run_in_executor(lambda: Volume(
            args.dir, collection, args.volumeId,
            replica_placement=ReplicaPlacement.parse(
                st.get("replication", "000")),
            ttl=t.TTL.parse(st.get("ttl", ""))))
        need_full = (
            v.super_block.compaction_revision
            != st["compaction_revision"]
            or v.last_append_at_ns > st["last_append_at_ns"])
        if need_full:
            base = v.file_name()
            v.close()
            # .idx before .dat (see h_volume_copy): a racing write then at
            # most leaves extra .dat tail past the last copied idx entry,
            # which the open-time integrity check truncates. Download to
            # .tmp and swap both only on success so a mid-fetch failure
            # leaves the previous backup intact.
            tmps: list[tuple[str, str]] = []
            try:
                for ext in (".idx", ".dat"):
                    tmp = base + ext + ".tmp"
                    async with http.get(
                            tls.url(args.server, "/admin/file"),
                            params={"volume": str(args.volumeId),
                                    "collection": collection,
                                    "ext": ext}) as resp:
                        if resp.status != 200:
                            raise RuntimeError(
                                f"fetch {ext}: http {resp.status}")
                        # volume-sized files: open/write/close leave
                        # the loop the http session runs on
                        f = await tracing.run_in_executor(
                            open, tmp, "wb")
                        try:
                            async for chunk in \
                                    resp.content.iter_chunked(1 << 20):
                                await tracing.run_in_executor(
                                    f.write, chunk)
                        finally:
                            await tracing.run_in_executor(f.close)
                    tmps.append((tmp, base + ext))
            except (RuntimeError, aiohttp.ClientError, OSError) as e:
                for tmp, _ in tmps:
                    if os.path.exists(tmp):
                        await tracing.run_in_executor(os.remove, tmp)
                print(f"full copy failed: {e}")
                sys.exit(1)
            # swap .dat before .idx: a crash in between leaves old .idx +
            # new (superset) .dat, which the open-time integrity check
            # truncates to a consistent state; the reverse order is fatal
            for tmp, final in reversed(tmps):
                await tracing.run_in_executor(os.replace, tmp, final)
            v = await tracing.run_in_executor(lambda: Volume(
                args.dir, collection, args.volumeId,
                create_if_missing=False))
            size = await tracing.run_in_executor(v.data_size)
            print(f"full copy of volume {args.volumeId}: "
                  f"{size} bytes")
        else:
            since = v.last_append_at_ns
            applied = 0
            dec = vb.FrameDecoder()
            async with http.get(
                    tls.url(args.server, "/admin/volume/tail"),
                    params={"volume": str(args.volumeId),
                            "since_ns": str(since)}) as resp:
                if resp.status != 200:
                    print(f"tail from {args.server}: http {resp.status}")
                    sys.exit(1)
                def _apply_batch(records):
                    for n, is_delete in records:
                        vb.apply_needle(v, n, is_delete)

                async for chunk in resp.content.iter_chunked(1 << 20):
                    batch = list(dec.feed(chunk))
                    if batch:
                        # one executor hop per decoded chunk, not per
                        # record — a multi-million-record catch-up would
                        # otherwise pay submit/wakeup latency every needle
                        await tracing.run_in_executor(_apply_batch, batch)
                        applied += len(batch)
            print(f"applied {applied} records to volume {args.volumeId} "
                  f"(since_ns={since})")
        v.close()


def _run_fix(args) -> None:
    """Rebuild .idx by scanning .dat (command/fix.go)."""
    from .storage import types as t
    from .storage.needle_map import pack_entry
    from .storage.volume import Volume
    v = Volume(args.dir, args.collection, args.volumeId,
               create_if_missing=False)
    entries: dict[int, tuple[int, int]] = {}

    def visit(n, offset):
        if n.size > 0 or n.data:
            entries[n.id] = (offset, n.size)
        else:
            entries[n.id] = (0, t.TOMBSTONE_FILE_SIZE)
    v.scan(visit)
    idx_path = v.file_name() + ".idx"
    with open(idx_path, "wb") as f:
        for key, (off, size) in entries.items():
            f.write(pack_entry(key, off, size))
    print(f"rebuilt {idx_path} with {len(entries)} entries")
    v.close()


def _run_export(args) -> None:
    """List needles as JSON lines, or -o out.tar to dump contents
    (reference: weed export w/ -o tar, -fileNameFormat, -newer,
    command/export.go)."""
    import fnmatch
    import io
    import tarfile

    from .storage.volume import Volume
    v = Volume(args.dir, args.collection, args.volumeId,
               create_if_missing=False)
    newer_ts = 0.0
    if args.newer:
        newer_ts = time.mktime(
            time.strptime(args.newer, "%Y-%m-%dT%H:%M:%S"))
    tar = tarfile.open(args.output, "w") if args.output else None
    exported = 0

    class _LimitReached(Exception):
        pass

    def want(n) -> bool:
        name = n.name.decode(errors="replace")
        if args.pattern and not fnmatch.fnmatch(name, args.pattern):
            return False
        if newer_ts and getattr(n, "last_modified", 0) < newer_ts:
            return False
        return True

    from .storage import types as _t

    def _is_live(n, offset) -> bool:
        # a scanned record is live only if the needle map still points at
        # THIS offset (overwritten/deleted data must not be resurrected)
        nv = v.nm.get(n.id)
        return (nv is not None and nv.offset == offset
                and nv.size != _t.TOMBSTONE_FILE_SIZE)

    def visit(n, offset):
        nonlocal exported
        if args.limit > 0 and exported >= args.limit:
            raise _LimitReached
        kind = "tombstone" if n.size == 0 and not n.data else "needle"
        if tar is None:
            # listing mode keeps every historical record (incl.
            # tombstones) — it is the audit view of the raw .dat
            if kind == "needle" and not want(n):
                return
            print(json.dumps({
                "key": n.id, "cookie": n.cookie, "size": n.size,
                "offset": offset, "name": n.name.decode(errors="replace"),
                "mime": n.mime.decode(errors="replace"), "type": kind,
                "live": kind == "needle" and _is_live(n, offset)}))
            exported += 1
            return
        if kind == "tombstone" or not want(n) or not _is_live(n, offset):
            return
        exported += 1
        member = args.fileNameFormat.format(
            name=n.name.decode(errors="replace") or f"{n.id:x}",
            id=f"{n.id:x}", mime=n.mime.decode(errors="replace"))
        info = tarfile.TarInfo(member)
        info.size = len(n.data)
        info.mtime = int(getattr(n, "last_modified", 0) or 0)
        tar.addfile(info, io.BytesIO(bytes(n.data)))

    try:
        v.scan(visit)
    except _LimitReached:
        pass
    v.close()
    if tar is not None:
        tar.close()
        print(f"exported {exported} files to {args.output}")


def _run_compact(args) -> None:
    from .storage import vacuum
    from .storage.volume import Volume
    v = Volume(args.dir, args.collection, args.volumeId,
               create_if_missing=False)
    before = v.data_size()
    vacuum.compact(v)
    vacuum.commit_compact(v)
    print(f"compacted volume {args.volumeId}: {before} -> {v.data_size()} "
          f"bytes")
    v.close()


_SCAFFOLDS = {
    "security": """# security.toml (reference: weed scaffold -config=security)
[jwt.signing]
key = ""            # base64 or raw secret; empty disables write tokens
expires_after_seconds = 10

[tls]
# mutual TLS for the inter-server mesh (master + volume servers), like the
# reference's [grpc.*] sections (weed/security/tls.go). Client-facing
# surfaces (filer HTTP, S3, WebDAV) stay plaintext + JWT so standard
# clients keep working. All three paths required.
ca = ""             # CA certificate that signed every server cert
cert = ""           # this process's certificate
key = ""            # this process's private key
require_client_cert = true
""",
    "master": """# master.toml
[master.maintenance]
scripts = \"\"\"
  ec.encode -fullPercent=95 -quietFor=1h
  ec.rebuild -force
  ec.balance -force
  volume.balance -force
  # parity scrub reads every EC stripe — run it on its own master.toml
  # with a long sleep_minutes (e.g. daily), not every cycle:
  # ec.verify -collection important
\"\"\"
sleep_minutes = 17
[master.sequencer]
type = "memory"
""",
    "filer": """# filer.toml
[memory]
enabled = false
[sqlite]
enabled = true
path = "./filer.db"
""",
    "notification": """# notification.toml (weed scaffold -config=notification)
# exactly ONE queue may be enabled; the filer publishes an
# EventNotification per meta change to it (filer_notify.go:9-31)

[notification.log]
enabled = false

[notification.file]
enabled = false
path = "./filer.events"

[notification.sqlite]
enabled = false
path = "./filer.events.db"

[notification.kafka]
enabled = false
hosts = ["localhost:9092"]
topic = "seaweedfs_filer"

[notification.aws_sqs]
enabled = false
region = "us-east-2"
sqs_queue_name = "my_sqs_queue"

[notification.google_pub_sub]
enabled = false
project_id = ""
topic = "seaweedfs_filer_topic"
""",
    "replication": """# replication.toml (weed scaffold -config=replication)
# Consumed by `weed-tpu filer.replicate` when the corresponding flags
# are not given. Every key maps 1:1 to a flag (see -h).

[replication]
# -notify: subscription input
#   file:<path> | sqlite:<path> | kafka:<hosts>/<topic>[@offsets] |
#   sqs:<region>/<queue> | pubsub:<project>/<topic>
notify = "file:./filer.events"
# -sourceMaster / -sourceDir: cluster + subtree the events refer to
sourceMaster = "localhost:9333"
sourceDir = "/"
# -sink / -sinkDir: replication target
#   filer:<filerHost:port>@<targetMaster> | s3:<endpointUrl>/<bucket>
#   | local:<dir>
sink = "local:/data/backup"
sinkDir = "/"
# -progress: consumed-offset checkpoint file (resume-after-restart)
progress = "./replicate.progress"
""",
}


def _discover_security_toml() -> None:
    """Discovered security.toml enables mTLS when [tls] is configured
    (util/config.go:28-45 search order)."""
    found = _find_config_toml("security")
    if found is not None:
        from .util import glog
        if tls.configure_from_toml(found[0], found[1]):
            glog.info("mTLS enabled from %s", found[0])


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    # the worker supervisor re-executes this same command line with
    # -workerIndex appended; remember it when given programmatically
    args._raw_argv = list(argv) if argv is not None else None
    # SWTPU_OFFSET_BYTES=5: the reference's 5BytesOffset build tag as a
    # runtime switch (8TB volumes; offset_5bytes.go:14-16). Process-wide,
    # set before any volume or index file is opened.
    env_off = os.environ.get("SWTPU_OFFSET_BYTES")
    if env_off:
        from .storage import types as _types
        _types.set_offset_size(int(env_off))
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # the axon sitecustomize force-registers the TPU tunnel and
        # IGNORES the JAX_PLATFORMS env var; only jax.config wins at
        # backend-init time. Without this, an explicit cpu request still
        # dials the tunnel and an EC endpoint can hang in backend init.
        import jax
        jax.config.update("jax_platforms", "cpu")
    if hasattr(args, "verbosity"):
        from .util import glog
        glog.init(verbosity=args.verbosity,
                  log_dir=args.logdir or None,
                  logtostderr=args.logtostderr)
        tracing.init(sample=args.trace_sample, slow_ms=args.trace_slowms,
                     ring=args.trace_ring)
        from .stats import slo, timeline
        timeline.init(interval_s=args.timeline_interval,
                      ring=args.timeline_ring)
        try:
            slo.init(args.slo)
        except ValueError as e:
            # refuse to start guarding nothing: a typo'd objective
            # silently ignored would "pass" every soak
            raise SystemExit(str(e))
        from . import qos
        try:
            if args.qos_tenant:
                qos.init_admission(args.qos_tenant,
                                   lag_shed_ms=args.qos_shed_lagms,
                                   wait_shed_ms=args.qos_shed_waitms)
        except ValueError as e:
            # same refusal discipline as a typo'd -slo: a malformed
            # tenant spec silently dropped would leave that tenant in
            # the default class and "pass" every abuse soak
            raise SystemExit(str(e))
        if args.qos_mbps > 0:
            qos.init_arbiter(args.qos_mbps, floor=args.qos_floor)
        if args.slo and not timeline.enabled():
            # same hazard as a typo'd spec: with the recorder off no
            # window is ever snapped, slo.tick() never runs, and
            # /debug/health reports ok forever no matter the damage
            raise SystemExit(
                "-slo needs the flight recorder: -timeline.interval 0 "
                "disables the timeline the burn engine evaluates")
        if args.slo:
            # the ring must hold the slow burn horizon: 360 windows at
            # -timeline.interval 1 is only 360s of history for a 600s
            # window — silently evaluating the "slow" burn over less
            # defeats its blip-suppression role
            needed = slo.windows_needed(minimum=0)
            if needed > args.timeline_ring:
                from .util import glog
                glog.info("-timeline.ring %d too small for the %ds SLO "
                          "slow window at interval %gs; using %d",
                          args.timeline_ring, int(slo.SLOW_WINDOW_S),
                          args.timeline_interval, needed)
                timeline.init(interval_s=args.timeline_interval,
                              ring=needed)
        if os.environ.get("WEED_WORKER_RESPAWNS"):
            # set by the -workers supervisor on every respawn (the
            # supervisor itself serves no HTTP, so the respawned
            # worker journals the event where /debug/events can see
            # it and /debug/health can correlate it)
            from .util import events
            try:
                n_respawns = int(os.environ["WEED_WORKER_RESPAWNS"])
            except ValueError:
                n_respawns = -1
            events.record("worker_respawn",
                          index=getattr(args, "workerIndex", -1),
                          respawns=n_respawns)
        if args.cpuprofile or args.memprofile:
            from .util.pprof import setup_profiling
            # -workers N: each worker suffixes the dump path with its
            # index, or all N processes would clobber one file
            setup_profiling(args.cpuprofile, args.memprofile,
                            worker_index=getattr(args, "workerIndex", -1))
        if getattr(args, "profile_hz", 0):
            # continuous sampler: per process, so every -workers
            # sibling samples itself and /debug/profile merges them
            from .stats import profiler
            profiler.init(args.profile_hz)
            profiler.start()
        if os.environ.get("WEED_FAILPOINTS"):
            # armed at import by util/failpoints; an injected-fault run
            # must never be mistakable for a healthy one in the logs
            from .util import failpoints
            glog.warning("FAILPOINTS ARMED: %s",
                         ", ".join(f"{a['site']}={a['action']}"
                                   for a in failpoints.list_armed()))
    _discover_security_toml()
    if args.cmd == "version":
        from . import __version__
        print(f"seaweedfs_tpu {__version__}")
        return
    if args.cmd == "scaffold":
        try:
            print(_SCAFFOLDS[args.config])
        except BrokenPipeError:
            os._exit(0)
        return
    if args.cmd == "fix":
        _run_fix(args)
        return
    if args.cmd == "export":
        _run_export(args)
        return
    if args.cmd == "compact":
        _run_compact(args)
        return
    if args.cmd == "mount":
        from .filer.filer import Filer
        from .mount.fuse_adapter import mount as fuse_mount
        from .mount.wfs import MountOptions
        fuse_mount(
            Filer(args.filerStore), args.master, args.dir,
            MountOptions(collection=args.collection,
                         replication=args.replication, ttl=args.ttl,
                         chunk_size_limit=args.chunkSizeLimitMB << 20))
        return
    if args.cmd == "bench-ec":
        import subprocess
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        subprocess.run([sys.executable, os.path.join(repo, "bench.py")])
        return
    runners = {
        "master": _run_master, "volume": _run_volume, "filer": _run_filer,
        "s3": _run_s3, "server": _run_server, "upload": _run_upload,
        "download": _run_download, "shell": _run_shell,
        "benchmark": _run_benchmark, "backup": _run_backup,
        "webdav": _run_webdav, "filer.replicate": _run_filer_replicate,
        "filer.copy": _run_filer_copy,
    }
    try:
        asyncio.run(runners[args.cmd](args))
    except KeyboardInterrupt:
        pass
    except BrokenPipeError:
        # stdout piped to a closed reader (e.g. `| head`)
        os._exit(0)


if __name__ == "__main__":
    main()
