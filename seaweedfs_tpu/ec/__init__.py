"""Erasure coding: GF(256) math, RS(10,4) encoders, stripe layout."""

from .gf import (  # noqa: F401
    DATA_SHARDS,
    PARITY_SHARDS,
    TOTAL_SHARDS,
)
