"""Stripe-batch EC execution engine — one transform dispatch per B windows.

The paper's headline transform (RS(10,4) GF(2^8) as a batched
Cauchy-matrix multiply over device-resident stripe batches) is only as
fast as its *scheduling*: window-at-a-time dispatch is latency-bound
long before the field math matters (PAPERS.md 2108.02692 and 1312.5155
draw the same conclusion at CPU scale). This module is the shared
execution engine for the three bulk EC paths — whole-volume encode
(`pipeline.encode_volume`), parity scrub (`EcVolume.verify_parity` /
`ec/scrub.py`) and whole-volume rebuild (`pipeline.rebuild_ec_files`):

* gather **B stripe windows into one `(B, k, L)` uint8 block**;
* run encode / verify / reconstruct as **ONE batched transform per
  block** (`encoder.transform_batch`: the CPU backends flatten the
  batch into the byte axis — the GF transform is columnwise, so the
  batch dim is free — while `JaxEncoder` jits a vmapped bitplane
  transform once per `(rows, k)` shape and shards the block along the
  batch dim via `NamedSharding(P('batch'))` when more than one device
  is attached);
* account every dispatch, pread and byte **deterministically** (the
  `stats` dicts below), so tools/bench_ec.py can gate the batching win
  on arithmetic instead of wall clock.

The GF(256) transform is independent per byte column, so batching is
*exact*: a `(B, k, L)` block transforms to the same bytes as B separate
`(k, L)` windows — the numpy per-window oracle remains the byte-identity
gate for every backend (tests/test_ec_batch.py).
"""

from __future__ import annotations

import numpy as np

# windows gathered per transform dispatch: enough to amortise dispatch
# latency into noise, small enough that python-side window bookkeeping
# stays trivial. The REQUESTED width; the resident-memory ceiling is
# clamp_batch_windows below.
DEFAULT_BATCH_WINDOWS = 8

# resident-byte ceiling for one gathered block. A batch width is a
# latency knob, not a licence to hold GBs: 8 x 14 rows x 4 MB windows
# would pin 448 MB (and ~double that with the read-ahead block), where
# the pre-batching paths peaked near one 8 MB buffer's 112 MB. Every
# bulk path clamps its effective width so a block stays under this
# budget — at large windows batching degrades gracefully toward the
# old per-window footprint instead of OOMing the host. The default
# 128 MB admits a full 8-window batch of the bulk paths' 1 MB default
# windows (8 x 14 x 1 MB = 112 MB — byte-for-byte the same payload
# per dispatch as the pre-batching 8 MB-buffer window, just batched);
# the background scrubber passes its own tighter budget because for
# it the bound is an I/O *burst* limit, not only memory.
BLOCK_BYTE_BUDGET = 128 << 20


def clamp_batch_windows(batch_windows: int, window_bytes: int,
                        rows: int, budget: int | None = None) -> int:
    """Effective batch width: the requested window count bounded so one
    (B, rows, window_bytes) block stays inside the byte budget
    (always at least 1 — a single window must still fit the old way)."""
    if batch_windows < 1:
        return 1
    if window_bytes <= 0 or rows <= 0:
        return batch_windows
    if budget is None:
        budget = BLOCK_BYTE_BUDGET
    return max(1, min(batch_windows, budget // (rows * window_bytes)))


def add_stat(stats: dict | None, **kv) -> None:
    """Accumulate deterministic accounting counters into an optional
    stats dict (windows / batches / dispatches / preads / bytes...)."""
    if stats is None:
        return
    for k, v in kv.items():
        stats[k] = stats.get(k, 0) + v


def transform_block(encoder, coeff: np.ndarray, block: np.ndarray,
                    stats: dict | None = None) -> np.ndarray:
    """Apply a (rows, k) GF(256) coefficient matrix to a (B, k, L)
    window block in ONE dispatch -> (B, rows, L) uint8."""
    return transform_block_async(encoder, coeff, block, stats)()


def transform_block_async(encoder, coeff: np.ndarray, block: np.ndarray,
                          stats: dict | None = None):
    """Launch the batched transform; returns a thunk yielding the
    (B, rows, L) numpy result. On the JAX backend the dispatch is
    asynchronous and the thunk blocks on readback, so the caller can
    overlap the NEXT block's preads with this block's device time —
    the same double-buffering contract as pipeline's per-window
    `_transform_buffers_async`, now per B windows."""
    block = np.asarray(block, np.uint8) if not hasattr(block, "devices") \
        else block
    add_stat(stats, dispatches=1, batches=1, windows=int(block.shape[0]),
             bytes_in=int(block.nbytes))
    out = encoder.transform_batch(coeff, block)
    return lambda: np.asarray(out)


def verify_block(encoder, block: np.ndarray,
                 stats: dict | None = None) -> list[bool]:
    """Recompute parity for a (B, k+m, L) block and compare against its
    stored parity rows in ONE dispatch -> per-window verdicts.

    Zero-padded tail windows verify clean by construction: parity of
    all-zero data is all-zero, which is exactly what a shard read past
    EOF returns for the stored rows."""
    block = np.asarray(block, np.uint8)
    add_stat(stats, dispatches=1, batches=1, windows=int(block.shape[0]),
             bytes_in=int(block.nbytes))
    return [bool(ok) for ok in encoder.verify_batch(block)]


def localize_corrupt_rows(encoder, rows: np.ndarray) -> "list[int]":
    """Pin a corrupt stripe window's rot to ONE shard row, if possible.

    `rows` is a (total_shards, L) uint8 window whose parity check
    failed. For each hypothesis "shard c is the corrupt one", shard c
    is reconstructed from k of the OTHER rows and the whole window is
    re-verified with the reconstruction substituted: with a single
    corrupt row only the true culprit's hypothesis makes the stripe
    consistent (any other hypothesis leaves the corrupt row in the
    equations, which then cannot all hold). Returns [culprit] when
    exactly one hypothesis survives, [] when the window is ambiguous
    (multi-shard rot) — the autopilot DEFERS unlocalized windows
    rather than guessing which copy to destroy.

    Cost: total_shards reconstruct+verify passes over ONE window, paid
    only for corrupt windows — rot is rare by construction.
    """
    from . import gf

    total = int(rows.shape[0])
    k = gf.DATA_SHARDS
    culprits: "list[int]" = []
    for c in range(total):
        sources = [s for s in range(total) if s != c][:k]
        coeff = gf.cached_shard_rows((c,), tuple(sources))
        from .pipeline import _transform_buffers
        rec = _transform_buffers(encoder, coeff,
                                 [np.ascontiguousarray(rows[s])
                                  for s in sources])[0]
        cand = np.array(rows, np.uint8, copy=True)
        cand[c] = np.frombuffer(
            np.asarray(rec, np.uint8).tobytes(), np.uint8)
        if bool(encoder.verify_batch(cand[None, :, :])[0]):
            culprits.append(c)
    return culprits if len(culprits) == 1 else []


def window_blocks(total_windows: int, batch_windows: int):
    """Yield (first_window_index, count) specs covering total_windows
    in ceil(total/batch) blocks — THE dispatch-count contract the
    bench smoke asserts."""
    if batch_windows < 1:
        batch_windows = 1
    wi = 0
    while wi < total_windows:
        count = min(batch_windows, total_windows - wi)
        yield wi, count
        wi += count
