"""EC volume: serve needle reads from shard files, with degraded-read
reconstruction when shards are missing.

Reference: ec_volume.go:24-72 (open .ecx/.ecj + shards),
ec_volume.go:183-228 (LocateEcShardNeedle + sorted-index search),
ec_shard.go:87-91 (shard ReadAt), store_ec.go:119-209 (interval gather,
local -> remote -> recover fallback), ec_volume_delete.go (tombstone in
.ecx + .ecj journal).

This class covers the local/in-process part; the volume server layer adds
the remote-shard gRPC-analog fetch. `fetch_remote` is the injection point:
fn(shard_id, offset, size) -> bytes | None.
"""

from __future__ import annotations

import os

import threading
from typing import Callable

from ..storage import types as t
from ..storage.needle import Needle
from ..storage.needle_map import SortedFileNeedleMap
from ..util import glog
from . import gf
from .locate import (LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, Interval,
                     locate_data)
from .pipeline import get_encoder, to_ext, _transform_buffers

import numpy as np


class EcVolumeError(Exception):
    pass


class NotFoundError(EcVolumeError):
    pass


class EcVolume:
    def __init__(self, dirname: str, collection: str, vid: int,
                 version: int = t.CURRENT_VERSION,
                 large_block: int = LARGE_BLOCK_SIZE,
                 small_block: int = SMALL_BLOCK_SIZE,
                 encoder=None,
                 fetch_remote: Callable[[int, int, int], bytes | None] | None = None,
                 fetch_remote_batch=None,
                 recover_cache=None):
        self.dir = dirname
        self.collection = collection
        self.vid = vid
        # degraded-read reconstruction cache (util/chunk_cache
        # LruByteCache, usually shared store-wide): keys carry the vid
        # so one cache serves every mounted EC volume. Shard bytes are
        # immutable once written (deletes tombstone the .ecx, never the
        # shards), so entries only go stale when shards are re-encoded
        # — the Store drops this vid's keys on EC mount/unmount.
        self._recover_cache = recover_cache
        self.version = version
        self.large_block = large_block
        self.small_block = small_block
        self._encoder = encoder
        self._default_encoder = None
        self._small_encoder = None
        self.fetch_remote = fetch_remote
        # batched form: fn([(sid, off, size), ...]) -> dict[sid, bytes]
        # | None — one request per remote HOLDER instead of one per
        # shard interval (the recover gather's network fan-out)
        self.fetch_remote_batch = fetch_remote_batch
        base = collection + "_" + str(vid) if collection else str(vid)
        self.base_name = os.path.join(dirname, base)
        self._ecx = SortedFileNeedleMap(self.base_name + ".ecx",
                                        writable=True)
        self._ecj = open(self.base_name + ".ecj", "ab")
        self._lock = threading.RLock()
        self.shards: dict[int, object] = {}
        for sid in range(gf.TOTAL_SHARDS):
            p = self.base_name + to_ext(sid)
            if os.path.exists(p):
                self.shards[sid] = open(p, "rb")

    # ---- index ----

    def find_needle(self, needle_id: int) -> tuple[int, int]:
        """Binary search .ecx -> (offset, size incl. tombstones); raises
        NotFoundError (SearchNeedleFromSortedIndex, ec_volume.go:203-228)."""
        raw = self._ecx.get_raw(needle_id)
        if raw is None:
            raise NotFoundError(f"needle {needle_id:x} not in ecx")
        return raw

    def delete_needle(self, needle_id: int) -> None:
        """Mark deleted in .ecx + journal to .ecj
        (DeleteNeedleFromEcx, ec_volume_delete.go:27-49)."""
        with self._lock:
            if self._ecx.mark_deleted(needle_id):
                self._ecj.write(needle_id.to_bytes(8, "big"))
                self._ecj.flush()

    # ---- data path ----

    @property
    def shard_size(self) -> int:
        for f in self.shards.values():
            return os.fstat(f.fileno()).st_size
        return 0

    @property
    def dat_size(self) -> int:
        return gf.DATA_SHARDS * self.shard_size

    # below this, a recover transform is dispatch-latency-bound and the
    # host AVX2/numpy path beats a device round trip (store_ec.go always
    # pays the CPU cost; we pay it only where it wins)
    SMALL_RECOVER_BYTES = 1 << 20

    def encoder(self, interval_size: int | None = None):
        if self._encoder is not None:  # explicit injection always wins
            return self._encoder
        if (interval_size is not None
                and interval_size < self.SMALL_RECOVER_BYTES):
            if self._small_encoder is None:
                from .encoder_cpu import CpuEncoder
                self._small_encoder = CpuEncoder()
            return self._small_encoder
        if self._default_encoder is None:
            self._default_encoder = get_encoder()
        return self._default_encoder

    def _read_shard_interval(self, sid: int, offset: int, size: int) -> bytes:
        """local shard -> remote fetch -> on-the-fly reconstruct
        (readOneEcShardInterval, store_ec.go:178-209)."""
        f = self.shards.get(sid)
        if f is not None:
            # pread: position-independent, safe under concurrent readers
            data = os.pread(f.fileno(), size, offset)
            if len(data) == size:
                return data
            return data + b"\x00" * (size - len(data))
        if self.fetch_remote is not None:
            data = self.fetch_remote(sid, offset, size)
            if data is not None:
                return data
        return self._recover_interval(sid, offset, size)

    def _recover_interval(self, want_sid: int, offset: int, size: int) -> bytes:
        """Gather the same interval from >=10 other shards and decode
        (recoverOneRemoteEcShardInterval, store_ec.go:319-373).

        Hot intervals of a lost shard are served from the
        reconstruction cache: repeated degraded reads of the same
        needle reuse the decoded bytes instead of re-gathering ten
        shards and re-running the GF(256) transform (the dominant
        degraded-read cost — arxiv 2306.10528)."""
        from ..util import tracing
        rc = self._recover_cache
        key = (self.vid, want_sid, offset, size)
        gen = None
        if rc is not None:
            cached = rc.get(key)
            if cached is not None:
                return cached
            # generation snapshot BEFORE gathering (EcRecoverCache; a
            # plain LruByteCache in tests has no generations): a
            # re-encode/remount racing this reconstruction bumps it and
            # the stale fill below is refused
            if hasattr(rc, "generation"):
                gen = rc.generation(self.vid)
        # traced as its own span: the GF(256) gather+decode is the
        # dominant degraded-read cost (arxiv 2306.10528) and must be
        # attributable per request, not only in aggregate
        with tracing.start("ec", "recover", vid=self.vid,
                           shard=want_sid) as sp:
            # local shards first (free), then ONE batched remote gather
            # for however many more the decode needs — the k-fetch
            # network fan-out collapses to one request per holder
            local: dict[int, bytes] = {}
            want_remote: list[int] = []
            for sid in range(gf.TOTAL_SHARDS):
                if sid == want_sid:
                    continue
                f = self.shards.get(sid)
                if f is not None and len(local) < gf.DATA_SHARDS:
                    raw = os.pread(f.fileno(), size, offset)
                    local[sid] = raw + b"\x00" * (size - len(raw))
                elif f is None:
                    want_remote.append(sid)
            remote: dict[int, bytes] = {}
            missing = gf.DATA_SHARDS - len(local)
            if missing > 0 and want_remote:
                batch = None
                if self.fetch_remote_batch is not None:
                    # only as many intervals as the decode still needs:
                    # over-asking would move (and pread) extra repair
                    # bytes on every holder; the per-shard fallback
                    # below covers holders that failed to serve
                    batch = self.fetch_remote_batch(
                        [(sid, offset, size)
                         for sid in want_remote[:missing]])
                if batch:
                    for sid in want_remote:
                        data = batch.get(sid)
                        if data is not None and len(remote) < missing:
                            remote[sid] = data
                if len(remote) < missing and self.fetch_remote is not None:
                    for sid in want_remote:
                        if sid in remote:
                            continue
                        if len(remote) >= missing:
                            break
                        data = self.fetch_remote(sid, offset, size)
                        if data is not None:
                            remote[sid] = data
            merged = {**local, **remote}
            bufs: list[np.ndarray] = []
            rows: list[int] = []
            for sid in sorted(merged):
                if len(rows) == gf.DATA_SHARDS:
                    break
                rows.append(sid)
                bufs.append(np.frombuffer(merged[sid], np.uint8))
            sp.set("shards", list(rows))
            if len(rows) < gf.DATA_SHARDS:
                raise EcVolumeError(
                    f"cannot recover shard {want_sid}: only {len(rows)} "
                    f"sources available")
            glog.V(3).infof(
                "ec recover vid=%d shard=%d off=%d size=%d from %s",
                self.vid, want_sid, offset, size, rows)
            coeff = gf.shard_rows([want_sid], rows)
            out = _transform_buffers(self.encoder(size), coeff, bufs)
            data = np.asarray(out[0], np.uint8).tobytes()
            sp.nbytes = len(data)
            if rc is not None:
                if gen is not None:
                    rc.put_fenced(key, data, gen)
                else:
                    rc.put(key, data)
            return data

    def verify_parity(self, window_size: int = 4 << 20) -> dict:
        """Scrub: recompute RS(10,4) parity over every stripe window and
        compare against the stored parity shards — a whole-volume
        bit-rot check that runs as the same GF(256) device transform the
        encoder uses (the reference has no equivalent; its integrity
        stops at per-needle CRCs on read, needle/crc.go).

        Missing local shards are listed (they verify via rebuild, not
        here); windows containing RECOVERED rows can't add evidence and
        are flagged. Returns {"windows", "bad_windows": [offsets],
        "missing_shards": [sids], "shard_size"}."""
        import numpy as np

        ssize = self.shard_size
        missing = [sid for sid in range(gf.TOTAL_SHARDS)
                   if sid not in self.shards
                   and (self.fetch_remote is None
                        or self.fetch_remote(sid, 0, 1) is None)]
        bad: list[int] = []
        recovered = len(missing) > 0
        windows = 0
        for off in range(0, ssize, window_size):
            w = min(window_size, ssize - off)
            rows = [np.frombuffer(
                self._read_shard_interval(sid, off, w), np.uint8)
                for sid in range(gf.TOTAL_SHARDS)]
            windows += 1
            enc = self.encoder(w)
            from .encoder_cpu import CpuEncoder
            if isinstance(enc, CpuEncoder):
                ok = enc.verify(rows)
            else:
                ok = enc.verify(np.stack(rows))
            if not ok:
                bad.append(off)
        return {"windows": windows, "bad_windows": bad,
                "missing_shards": missing, "shard_size": ssize,
                "used_recovered_rows": recovered}

    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        """Locate via .ecx, gather stripe intervals, parse + CRC-check
        (ReadEcShardNeedle, store_ec.go:119-153)."""
        with self._lock:
            offset, size = self.find_needle(needle_id)
            if size == t.TOMBSTONE_FILE_SIZE:
                raise NotFoundError(f"needle {needle_id:x} deleted")
            record_len = t.actual_size(size, self.version)
            intervals = locate_data(self.large_block, self.small_block,
                                    self.dat_size, offset, record_len)
            parts = []
            for iv in intervals:
                sid, soff = iv.to_shard_and_offset(self.large_block,
                                                   self.small_block)
                parts.append(self._read_shard_interval(sid, soff, iv.size))
            blob = b"".join(parts)
        n = Needle.from_bytes(blob, self.version)
        if cookie is not None and n.cookie != cookie:
            raise NotFoundError(f"cookie mismatch for {needle_id:x}")
        return n

    def close(self) -> None:
        self._ecx.close()
        self._ecj.close()
        for f in self.shards.values():
            f.close()

    def destroy(self) -> None:
        self.close()
        for ext in [".ecx", ".ecj"] + [to_ext(i) for i in range(gf.TOTAL_SHARDS)]:
            p = self.base_name + ext
            if os.path.exists(p):
                os.remove(p)
