"""EC volume: serve needle reads from shard files, with degraded-read
reconstruction when shards are missing.

Reference: ec_volume.go:24-72 (open .ecx/.ecj + shards),
ec_volume.go:183-228 (LocateEcShardNeedle + sorted-index search),
ec_shard.go:87-91 (shard ReadAt), store_ec.go:119-209 (interval gather,
local -> remote -> recover fallback), ec_volume_delete.go (tombstone in
.ecx + .ecj journal).

This class covers the local/in-process part; the volume server layer adds
the remote-shard gRPC-analog fetch. `fetch_remote` is the injection point:
fn(shard_id, offset, size) -> bytes | None.
"""

from __future__ import annotations

import os

import threading
from typing import Callable

from ..storage import types as t
from ..storage.needle import Needle
from ..storage.needle_map import SortedFileNeedleMap
from ..util import failpoints, glog
from . import gf
from .locate import (LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, Interval,
                     locate_data)
from .pipeline import get_encoder, to_ext, _transform_buffers

import numpy as np


class EcVolumeError(Exception):
    pass


class NotFoundError(EcVolumeError):
    pass


class RepairPlan:
    """Survivor preference order for reconstructing lost shards of one
    (vid, missing-set): local rows first (free), then remote rows
    grouped so the batch gather touches as few holders as possible.

    The plan holds the ORDER only — which of the remote rows are
    actually fetched is decided per interval, after cached survivor
    bytes are consumed — so one plan serves every offset. Cached per
    missing-set on the EcVolume and invalidated on shard
    mount/unmount (and on a holder-map refresh, which can regroup the
    remote rows)."""

    __slots__ = ("local", "remote")

    def __init__(self, local: list[int], remote: list[int]):
        self.local = local
        self.remote = remote


def order_holder_groups(groups: dict) -> list[int]:
    """Remote-row preference order from {holder_key: [sids]}: largest
    holder groups first (the batch gather costs the fewest round trips
    for the bytes that must move), unknown-holder rows (None key)
    last, sids ascending within a group. THE shared ordering — both
    select_survivors and EcVolume._repair_plan build their remote tail
    through it, so the spec'd selection and the shipped plan cannot
    drift."""
    ordered = sorted(((sorted(g), key) for key, g in groups.items()),
                     key=lambda t: (t[1] is None, -len(t[0]),
                                    t[0][0] if t[0] else -1))
    return [sid for g, _ in ordered for sid in g]


def select_survivors(want_sid: int, local, cached=(), remote_groups=(),
                     k: int = gf.DATA_SHARDS) -> list[int]:
    """Choose exactly k survivor rows for reconstructing `want_sid`,
    cheapest bytes first (arxiv 2306.10528's selection step):

      1. local shards — zero bytes moved;
      2. cached survivor intervals — bytes already moved once, reused;
      3. remote shards, holder groups largest-first — the batch gather
         then costs the fewest round trips for the bytes it must move.

    remote_groups: iterable of sid groups, one per holder (a bare
    iterable of sids counts as one group each). Deterministic; raises
    EcVolumeError when fewer than k distinct survivors exist."""
    chosen: list[int] = []
    seen = {want_sid}

    def take(sids) -> bool:
        for sid in sids:
            if sid in seen:
                continue
            seen.add(sid)
            chosen.append(sid)
            if len(chosen) == k:
                return True
        return False

    groups = {i: (sorted(g) if isinstance(g, (list, tuple, set,
                                              frozenset)) else [g])
              for i, g in enumerate(remote_groups)}
    if take(sorted(local)) or take(sorted(cached)) \
            or take(order_holder_groups(groups)):
        return chosen
    raise EcVolumeError(
        f"cannot plan recovery of shard {want_sid}: only "
        f"{len(chosen)} survivors available, need {k}")


class EcVolume:
    def __init__(self, dirname: str, collection: str, vid: int,
                 version: int = t.CURRENT_VERSION,
                 large_block: int = LARGE_BLOCK_SIZE,
                 small_block: int = SMALL_BLOCK_SIZE,
                 encoder=None,
                 fetch_remote: Callable[[int, int, int], bytes | None] | None = None,
                 fetch_remote_batch=None,
                 recover_cache=None,
                 holder_peek=None,
                 refresh_holders=None,
                 small_recover_bytes: int | None = None):
        self.dir = dirname
        self.collection = collection
        self.vid = vid
        # degraded-read reconstruction cache (util/chunk_cache
        # LruByteCache, usually shared store-wide): keys carry the vid
        # so one cache serves every mounted EC volume. Shard bytes are
        # immutable once written (deletes tombstone the .ecx, never the
        # shards), so entries only go stale when shards are re-encoded
        # — the Store drops this vid's keys on EC mount/unmount.
        self._recover_cache = recover_cache
        self.version = version
        self.large_block = large_block
        self.small_block = small_block
        self._encoder = encoder
        self._default_encoder = None
        self._small_encoder = None
        self.fetch_remote = fetch_remote
        # batched form: fn([(sid, off, size), ...]) -> dict[sid, bytes]
        # | None — one request per remote HOLDER instead of one per
        # shard interval (the recover gather's network fan-out)
        self.fetch_remote_batch = fetch_remote_batch
        # repair planning hooks (volume server): holder_peek() returns
        # {sid: holder_key} for NON-local shards from the location
        # cache without any I/O (grouping remote rows by holder);
        # refresh_holders() forces one holder-map re-resolve after a
        # failed batch gather
        self.holder_peek = holder_peek
        self.refresh_holders = refresh_holders
        # device-vs-host recover crossover (-ec.smallrecover): below
        # this, a recover transform is dispatch-latency-bound and the
        # host path wins; tools/bench_ec.py measures the live value so
        # the default stays honest
        self.small_recover_bytes = (self.SMALL_RECOVER_BYTES
                                    if small_recover_bytes is None
                                    else int(small_recover_bytes))
        # per-(missing-set) repair plans; invalidated on shard
        # mount/unmount and holder-map refresh
        self._plans: dict[frozenset, RepairPlan] = {}
        base = collection + "_" + str(vid) if collection else str(vid)
        self.base_name = os.path.join(dirname, base)
        self._ecx = SortedFileNeedleMap(self.base_name + ".ecx",
                                        writable=True)
        self._ecj = open(self.base_name + ".ecj", "ab")
        self._lock = threading.RLock()
        self.shards: dict[int, object] = {}
        for sid in range(gf.TOTAL_SHARDS):
            p = self.base_name + to_ext(sid)
            if os.path.exists(p):
                self.shards[sid] = open(p, "rb")

    # ---- index ----

    def find_needle(self, needle_id: int) -> tuple[int, int]:
        """Binary search .ecx -> (offset, size incl. tombstones); raises
        NotFoundError (SearchNeedleFromSortedIndex, ec_volume.go:203-228)."""
        raw = self._ecx.get_raw(needle_id)
        if raw is None:
            raise NotFoundError(f"needle {needle_id:x} not in ecx")
        return raw

    def delete_needle(self, needle_id: int) -> None:
        """Mark deleted in .ecx + journal to .ecj
        (DeleteNeedleFromEcx, ec_volume_delete.go:27-49)."""
        with self._lock:
            if self._ecx.mark_deleted(needle_id):
                self._ecj.write(needle_id.to_bytes(8, "big"))
                self._ecj.flush()

    # ---- data path ----

    @property
    def shard_size(self) -> int:
        for f in self.shards.values():
            return os.fstat(f.fileno()).st_size
        return 0

    @property
    def dat_size(self) -> int:
        return gf.DATA_SHARDS * self.shard_size

    # below this, a recover transform is dispatch-latency-bound and the
    # host AVX2/numpy path beats a device round trip (store_ec.go always
    # pays the CPU cost; we pay it only where it wins). The DEFAULT for
    # the measured, per-volume `small_recover_bytes` (-ec.smallrecover)
    SMALL_RECOVER_BYTES = 1 << 20

    def encoder(self, interval_size: int | None = None):
        if self._encoder is not None:  # explicit injection always wins
            return self._encoder
        if (interval_size is not None
                and interval_size < self.small_recover_bytes):
            if self._small_encoder is None:
                from .encoder_cpu import CpuEncoder
                self._small_encoder = CpuEncoder()
            return self._small_encoder
        if self._default_encoder is None:
            self._default_encoder = get_encoder()
        return self._default_encoder

    def _read_shard_interval(self, sid: int, offset: int, size: int) -> bytes:
        """local shard -> remote fetch -> on-the-fly reconstruct
        (readOneEcShardInterval, store_ec.go:178-209)."""
        f = self.shards.get(sid)
        if f is not None:
            failpoints.sync_fail("ec.shard_read")
            # pread: position-independent, safe under concurrent readers
            data = os.pread(f.fileno(), size, offset)
            if len(data) == size:
                return data
            return data + b"\x00" * (size - len(data))
        if self.fetch_remote is not None:
            data = self.fetch_remote(sid, offset, size)
            if data is not None:
                return data
        return self._recover_interval(sid, offset, size)

    # ---- repair planning (minimal-fetch degraded reads) ----

    def invalidate_plans(self) -> None:
        """Drop cached repair plans: shard mount/unmount (the missing
        set moved) or a holder-map refresh (the remote grouping
        moved). Cheap — plans rebuild lazily on the next recover."""
        self._plans.clear()

    def _repair_plan(self, want_sid: int) -> RepairPlan:
        """The cached survivor preference order for the current
        missing-set (every shard with no local file). One plan serves
        every lost shard and every offset: `want_sid` is excluded at
        selection time, and which remote rows actually move is decided
        per interval after cached bytes are consumed."""
        local = sorted(self.shards)
        missing = frozenset(range(gf.TOTAL_SHARDS)) - frozenset(local)
        plan = self._plans.get(missing)
        if plan is not None:
            return plan
        holders: dict = {}
        if self.holder_peek is not None:
            try:
                holders = self.holder_peek() or {}
            except Exception as e:  # noqa: BLE001 — planning is an
                # optimization; a failed peek degrades to sid order
                glog.V(2).infof("ec plan holder peek vid=%d: %s",
                                self.vid, e)
        groups: dict[object, list[int]] = {}
        for sid in sorted(missing):
            groups.setdefault(holders.get(sid), []).append(sid)
        plan = RepairPlan(local, order_holder_groups(groups))
        self._plans[missing] = plan
        return plan

    def _recover_interval(self, want_sid: int, offset: int, size: int) -> bytes:
        """Gather k survivor rows of the same interval and decode
        (recoverOneRemoteEcShardInterval, store_ec.go:319-373) —
        minimal-fetch: the repair plan orders survivors local-first,
        then cached, then remote grouped by holder, and exactly the
        k rows the decode needs are read (arxiv 2306.10528).

        Hot intervals of a lost shard are served from the
        reconstruction cache; remotely fetched SURVIVOR rows are
        cached under the same keyspace, so recovering a second lost
        shard of the same stripe re-uses the bytes already moved
        instead of re-fetching them."""
        from ..util import tracing
        rc = self._recover_cache
        key = (self.vid, want_sid, offset, size)
        gen = None
        if rc is not None:
            cached = rc.get(key)
            if cached is not None:
                return cached
            # generation snapshot BEFORE gathering (EcRecoverCache; a
            # plain LruByteCache in tests has no generations): a
            # re-encode/remount racing this reconstruction bumps it and
            # the stale fill below is refused
            if hasattr(rc, "generation"):
                gen = rc.generation(self.vid)
        # traced as its own span: the GF(256) gather+decode is the
        # dominant degraded-read cost (arxiv 2306.10528) and must be
        # attributable per request, not only in aggregate
        with tracing.start("ec", "recover", vid=self.vid,
                           shard=want_sid) as sp:
            failpoints.sync_fail("ec.recover.read")
            plan = self._repair_plan(want_sid)
            k = gf.DATA_SHARDS
            got: dict[int, bytes] = {}
            stale_local: list[int] = []
            for sid in plan.local:
                if len(got) >= k:
                    break
                if sid == want_sid:
                    continue
                f = self.shards.get(sid)
                if f is None:
                    # unmounted between planning and this read: the
                    # shard may now live on a peer — demote it to a
                    # remote candidate instead of dropping the row
                    stale_local.append(sid)
                    continue
                raw = os.pread(f.fileno(), size, offset)
                got[sid] = raw + b"\x00" * (size - len(raw))
            n_local = len(got)
            # cached survivor intervals: bytes a previous recover of
            # ANOTHER lost shard already moved — free the second time
            if rc is not None and len(got) < k:
                for sid in plan.remote:
                    if len(got) >= k:
                        break
                    if sid == want_sid:
                        continue
                    b = rc.get((self.vid, sid, offset, size))
                    if b is not None and len(b) == size:
                        got[sid] = b
            n_cached = len(got) - n_local
            fetched: dict[int, bytes] = {}
            want_remote = [sid for sid in plan.remote
                           if sid != want_sid and sid not in got] \
                + stale_local
            refreshed = False

            def gather(cands: list[int], need: int) -> None:
                if need <= 0 or not cands or \
                        self.fetch_remote_batch is None:
                    return
                # only as many intervals as the decode still needs:
                # over-asking would move (and pread) extra repair
                # bytes on every holder
                batch = self.fetch_remote_batch(
                    [(sid, offset, size) for sid in cands[:need]])
                if not batch:
                    return
                taken = 0
                # `need` bounds THIS call's acceptance: the retry
                # gather after a partially-successful first batch must
                # still be able to admit its rows (the shared dict
                # already holds the first batch's)
                for sid in cands:
                    data = batch.get(sid)
                    if data is not None and taken < need:
                        fetched[sid] = data
                        taken += 1

            gather(want_remote, k - len(got))
            if len(got) + len(fetched) < k and want_remote:
                # the batch came back short: refresh the holder map
                # ONCE, then retry the remainder as a SECOND batch —
                # never a per-shard loop against the same stale
                # holders for every shard in the batch. (The wired
                # batch fetcher may itself have invalidated the map
                # already — either way the next resolve sees the
                # freshest state, so the retry is issued
                # unconditionally: one batched attempt costs at most
                # one wasted round trip, strictly cheaper than the
                # k-shortfall per-shard singles it preempts.)
                if self.refresh_holders is not None:
                    try:
                        self.refresh_holders()
                    except Exception as e:  # noqa: BLE001 — refresh is
                        # best-effort; the per-shard fallback still runs
                        glog.V(1).infof("ec holder refresh vid=%d: %s",
                                        self.vid, e)
                    refreshed = True
                    self.invalidate_plans()
                gather([sid for sid in want_remote
                        if sid not in fetched],
                       k - len(got) - len(fetched))
                # last resort: per-shard fetch for stragglers, against
                # the refreshed map
                if len(got) + len(fetched) < k \
                        and self.fetch_remote is not None:
                    for sid in want_remote:
                        if sid in fetched:
                            continue
                        if len(got) + len(fetched) >= k:
                            break
                        data = self.fetch_remote(sid, offset, size)
                        if data is not None:
                            fetched[sid] = data
            got.update(fetched)
            bufs: list[np.ndarray] = []
            rows: list[int] = []
            for sid in sorted(got):
                if len(rows) == k:
                    break
                rows.append(sid)
                bufs.append(np.frombuffer(got[sid], np.uint8))
            sp.set("shards", list(rows))
            if refreshed:
                sp.event("holder_refresh")
            if len(rows) < k:
                raise EcVolumeError(
                    f"cannot recover shard {want_sid}: only {len(rows)} "
                    f"sources available")
            glog.V(3).infof(
                "ec recover vid=%d shard=%d off=%d size=%d from %s "
                "(local=%d cached=%d fetched=%d)",
                self.vid, want_sid, offset, size, rows,
                n_local, n_cached, len(fetched))
            coeff = gf.cached_shard_rows((want_sid,), tuple(rows))
            out = _transform_buffers(self.encoder(size), coeff, bufs)
            data = np.asarray(out[0], np.uint8).tobytes()
            sp.nbytes = len(data)
            if rc is not None:
                if gen is not None:
                    rc.put_fenced(key, data, gen)
                    # survivor rows that moved over the network are
                    # worth keeping too: a follow-up recover of a
                    # DIFFERENT lost shard of this stripe reuses them
                    # (same fence — stale survivor bytes must lose to
                    # a re-encode exactly like decoded ones)
                    for sid, b in fetched.items():
                        rc.put_fenced((self.vid, sid, offset, size),
                                      b, gen)
                else:
                    rc.put(key, data)
                    for sid, b in fetched.items():
                        rc.put((self.vid, sid, offset, size), b)
            return data

    def read_window_block(self, offset: int, count: int, size: int,
                          strict: bool = False,
                          stats: dict | None = None) -> np.ndarray:
        """Gather `count` consecutive stripe windows of `size` bytes
        into one (count, 14, size) uint8 block — the scrub unit of the
        stripe-batch engine. ONE pread (or one remote fetch) per shard
        covers the whole block; rows past shard EOF read as zeros, and
        since the stored parity there is zeros too, padded tail windows
        verify clean by construction.

        strict=True (the scrubber) refuses to substitute a
        RECONSTRUCTED row when a holder stops serving mid-cycle:
        parity recomputed from rows derived from the other rows
        matches trivially, so a 'clean' verdict would claim evidence
        about bytes that were never examined — the unreachable shard
        raises EcVolumeError instead and the volume's pass is reported
        as an error, not a clean scan. strict=False keeps the
        verify_parity semantics (recovered rows allowed, flagged
        volume-wide via used_recovered_rows).

        The `scrub.read` failpoint (action `flip`) corrupts rows here,
        applied per WINDOW row exactly like the pre-batching path (one
        potential fire per window per shard) — the injection point the
        scrub soak uses to prove planted corruption is detected while
        foreground reads stay clean."""
        nbytes = count * size
        rows = []
        local_preads = 0
        remote_rows = 0
        for sid in range(gf.TOTAL_SHARDS):
            if sid in self.shards:
                local_preads += 1
            else:
                remote_rows += 1
            if strict and sid not in self.shards:
                data = (self.fetch_remote(sid, offset, nbytes)
                        if self.fetch_remote is not None else None)
                if data is None:
                    raise EcVolumeError(
                        f"shard {sid} unreachable mid-scrub: window "
                        f"{offset} has no evidence for it")
            else:
                data = self._read_shard_interval(sid, offset, nbytes)
            if len(data) < nbytes:
                data = data + b"\x00" * (nbytes - len(data))
            rows.append(np.frombuffer(data, np.uint8).reshape(count, size))
        from .batch import add_stat
        # preads = LOCAL shard reads only; rows served by a peer (or
        # reconstructed) are accounted as remote_rows — a degraded
        # volume's verify report must not claim disk reads it never did
        add_stat(stats, preads=local_preads, remote_rows=remote_rows,
                 bytes_read=nbytes * gf.TOTAL_SHARDS)
        block = np.stack(rows, axis=1)
        if failpoints.armed():
            # window-major, sid-ascending — the exact fire order of the
            # pre-batching per-window path, so `flip:N` grammars plant
            # corruption in the same windows batched or not
            for w in range(count):
                for sid in range(gf.TOTAL_SHARDS):
                    d = failpoints.corrupt("scrub.read",
                                           block[w, sid].tobytes())
                    if len(d) != size:  # truncate armed: keep row shape
                        d = d[:size] + b"\x00" * (size - len(d))
                    block[w, sid] = np.frombuffer(d, np.uint8)
        return block

    def verify_window_block(self, offset: int, count: int, size: int,
                            strict: bool = False,
                            stats: dict | None = None) -> list[bool]:
        """Recompute RS(10,4) parity over `count` consecutive stripe
        windows in ONE batched transform dispatch and compare against
        the stored parity rows -> per-window verdicts. Every backend
        answers through the same `verify_batch(block)` surface — no
        per-encoder branching."""
        from .batch import verify_block
        block = self.read_window_block(offset, count, size, strict, stats)
        return verify_block(self.encoder(count * size), block, stats)

    def verify_window(self, offset: int, size: int,
                      strict: bool = False) -> bool:
        """One-window verify — the count=1 case of
        verify_window_block (the scrub unit before stripe batching;
        kept as the /admin and test-facing primitive)."""
        return self.verify_window_block(offset, 1, size, strict)[0]

    def missing_shards(self) -> list[int]:
        """Shards neither local nor remotely fetchable (they verify via
        rebuild, not scrub)."""
        return [sid for sid in range(gf.TOTAL_SHARDS)
                if sid not in self.shards
                and (self.fetch_remote is None
                     or self.fetch_remote(sid, 0, 1) is None)]

    def verify_parity(self, window_size: int = 4 << 20,
                      batch_windows: int | None = None) -> dict:
        """Scrub: recompute RS(10,4) parity over every stripe window and
        compare against the stored parity shards — a whole-volume
        bit-rot check that runs as the same GF(256) device transform the
        encoder uses (the reference has no equivalent; its integrity
        stops at per-needle CRCs on read, needle/crc.go).

        Runs through the stripe-batch engine: `batch_windows` windows
        per transform dispatch (ceil(W/B) dispatches per volume; the
        tail block zero-pads past shard EOF, which verifies clean by
        construction). Missing local shards are listed (they verify
        via rebuild, not here); windows containing RECOVERED rows
        can't add evidence and are flagged. Returns {"windows",
        "bad_windows": [offsets], "missing_shards": [sids],
        "shard_size", "batches", "dispatches", "preads"}."""
        from .batch import (DEFAULT_BATCH_WINDOWS, clamp_batch_windows,
                            window_blocks)
        if batch_windows is None:
            batch_windows = DEFAULT_BATCH_WINDOWS
        batch_windows = clamp_batch_windows(batch_windows, window_size,
                                            gf.TOTAL_SHARDS)
        ssize = self.shard_size
        missing = self.missing_shards()
        bad: list[int] = []
        n_windows = -(-ssize // window_size) if ssize else 0
        stats: dict = {}
        for wi, count in window_blocks(n_windows, batch_windows):
            off = wi * window_size
            for i, ok in enumerate(
                    self.verify_window_block(off, count, window_size,
                                             stats=stats)):
                if not ok:
                    bad.append(off + i * window_size)
        return {"windows": n_windows, "bad_windows": bad,
                "missing_shards": missing, "shard_size": ssize,
                "batches": stats.get("batches", 0),
                "dispatches": stats.get("dispatches", 0),
                "preads": stats.get("preads", 0),
                "remote_rows": stats.get("remote_rows", 0),
                "used_recovered_rows": len(missing) > 0}

    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        """Locate via .ecx, gather stripe intervals, parse + CRC-check
        (ReadEcShardNeedle, store_ec.go:119-153)."""
        with self._lock:
            offset, size = self.find_needle(needle_id)
            if size == t.TOMBSTONE_FILE_SIZE:
                raise NotFoundError(f"needle {needle_id:x} deleted")
            record_len = t.actual_size(size, self.version)
            intervals = locate_data(self.large_block, self.small_block,
                                    self.dat_size, offset, record_len)
            parts = []
            for iv in intervals:
                sid, soff = iv.to_shard_and_offset(self.large_block,
                                                   self.small_block)
                parts.append(self._read_shard_interval(sid, soff, iv.size))
            blob = b"".join(parts)
        n = Needle.from_bytes(blob, self.version)
        if cookie is not None and n.cookie != cookie:
            raise NotFoundError(f"cookie mismatch for {needle_id:x}")
        return n

    def close(self) -> None:
        self._ecx.close()
        self._ecj.close()
        for f in self.shards.values():
            f.close()

    def destroy(self) -> None:
        self.close()
        for ext in [".ecx", ".ecj"] + [to_ext(i) for i in range(gf.TOTAL_SHARDS)]:
            p = self.base_name + ext
            if os.path.exists(p):
                os.remove(p)
