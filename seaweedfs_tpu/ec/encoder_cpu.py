"""Pure-numpy RS(10,4) encoder — CPU baseline and correctness oracle.

Equivalent role to klauspost/reedsolomon's Encoder on the host
(reference call sites: ec_encoder.go:192, store_ec.go:322). The TPU path in
encoder_jax.py must match this byte-for-byte; bench.py uses this as the
host baseline the TPU kernel is measured against.
"""

from __future__ import annotations

import numpy as np

from . import gf


class CpuEncoder:
    """Table-lookup GF(256) encoder, vectorized with numpy.

    API mirrors the reedsolomon.Encoder surface the reference uses:
    encode / verify / reconstruct / reconstruct_data.
    Shards are a list of equal-length byte arrays (or None for missing).
    """

    def __init__(self, data_shards: int = gf.DATA_SHARDS,
                 parity_shards: int = gf.PARITY_SHARDS,
                 use_native: bool | None = None):
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        # native C kernel (AVX2 PSHUFB, native/gf256.c) when built; the
        # numpy path stays as the always-available correctness oracle
        if use_native is None:
            from ..native import gf256 as _native
            use_native = _native.available()
        self.use_native = use_native
        # Copy out of the lru_cache so instance mutation can't poison the
        # process-global matrix shared with every other encoder.
        self.matrix = gf.rs_matrix(self.k, self.n).copy()
        self.parity = self.matrix[self.k:]

    # -- core matmul ------------------------------------------------------

    def _apply(self, coeff: np.ndarray,
               inputs: list[np.ndarray]) -> list[np.ndarray]:
        if self.use_native and inputs and inputs[0].ndim == 1:
            from ..native import gf256 as _native
            return _native.transform(coeff, inputs)
        return self._apply_numpy(coeff, inputs)

    @staticmethod
    def _apply_numpy(coeff: np.ndarray,
                     inputs: list[np.ndarray]) -> list[np.ndarray]:
        """rows_out[r] = XOR_i mul_table(coeff[r,i])[inputs[i]]."""
        rows, k = coeff.shape
        assert k == len(inputs)
        out = []
        for r in range(rows):
            acc = np.zeros_like(inputs[0])
            for i in range(k):
                c = int(coeff[r, i])
                if c == 0:
                    continue
                elif c == 1:
                    acc ^= inputs[i]
                else:
                    acc ^= gf.mul_table(c)[inputs[i]]
            out.append(acc)
        return out

    # -- public API -------------------------------------------------------

    def encode(self, shards: list[np.ndarray | bytes | None]) -> list[np.ndarray]:
        """Compute parity from shards[:k]; returns a fresh list of k+m
        writable arrays (any parity entries passed in are ignored)."""
        data = [np.frombuffer(s, dtype=np.uint8).copy()
                if isinstance(s, (bytes, bytearray, memoryview))
                else np.asarray(s, dtype=np.uint8) for s in shards[:self.k]]
        parity = self._apply(self.parity, data)
        return data + parity

    def verify(self, shards: list[np.ndarray]) -> bool:
        if len(shards) != self.n:
            return False
        data = [np.asarray(s, dtype=np.uint8) for s in shards[:self.k]]
        parity = self._apply(self.parity, data)
        for got, want in zip(shards[self.k:], parity):
            if not np.array_equal(np.asarray(got, dtype=np.uint8), want):
                return False
        return True

    def reconstruct(self, shards: list[np.ndarray | None],
                    data_only: bool = False) -> list[np.ndarray]:
        """Rebuild missing (None) shards in place semantics; returns full list.

        Needs >= k present shards (reference guard:
        command_ec_rebuild.go:110 treats <10 as unrepairable).
        """
        present = [i for i, s in enumerate(shards) if s is not None]
        missing = [i for i, s in enumerate(shards) if s is None]
        if not missing:
            return [np.asarray(s, dtype=np.uint8) for s in shards]
        if len(present) < self.k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {self.k}")
        use = present[:self.k]
        if data_only:
            missing = [i for i in missing if i < self.k]
        inputs = [np.asarray(shards[i], dtype=np.uint8) for i in use]
        coeff = gf.shard_rows(missing, use, self.k, self.n)
        rebuilt = self._apply(coeff, inputs)
        out = [None if s is None else np.asarray(s, dtype=np.uint8)
               for s in shards]
        for idx, row in zip(missing, rebuilt):
            out[idx] = row
        return out

    def reconstruct_data(self, shards: list[np.ndarray | None]) -> list[np.ndarray]:
        """Rebuild only the k data shards (reference: ReconstructData,
        store_ec.go:322 degraded-read path)."""
        return self.reconstruct(shards, data_only=True)
