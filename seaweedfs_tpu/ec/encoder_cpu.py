"""Pure-numpy RS(10,4) encoder — CPU baseline and correctness oracle.

Equivalent role to klauspost/reedsolomon's Encoder on the host
(reference call sites: ec_encoder.go:192, store_ec.go:322). The TPU path in
encoder_jax.py must match this byte-for-byte; bench.py uses this as the
host baseline the TPU kernel is measured against.
"""

from __future__ import annotations

import numpy as np

from . import gf


class CpuEncoder:
    """Table-lookup GF(256) encoder, vectorized with numpy.

    API mirrors the reedsolomon.Encoder surface the reference uses:
    encode / verify / reconstruct / reconstruct_data.
    Shards are a list of equal-length byte arrays (or None for missing).
    """

    def __init__(self, data_shards: int = gf.DATA_SHARDS,
                 parity_shards: int = gf.PARITY_SHARDS,
                 use_native: bool | None = None):
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        # native C kernel (AVX2 PSHUFB, native/gf256.c) when built; the
        # numpy path stays as the always-available correctness oracle
        if use_native is None:
            from ..native import gf256 as _native
            use_native = _native.available()
        self.use_native = use_native
        # Copy out of the lru_cache so instance mutation can't poison the
        # process-global matrix shared with every other encoder.
        self.matrix = gf.rs_matrix(self.k, self.n).copy()
        self.parity = self.matrix[self.k:]

    # -- core matmul ------------------------------------------------------

    def _apply(self, coeff: np.ndarray,
               inputs: list[np.ndarray]) -> list[np.ndarray]:
        if self.use_native and inputs and inputs[0].ndim == 1:
            from ..native import gf256 as _native
            return _native.transform(coeff, inputs)
        return self._apply_numpy(coeff, inputs)

    @staticmethod
    def _apply_numpy(coeff: np.ndarray,
                     inputs: list[np.ndarray]) -> list[np.ndarray]:
        """rows_out[r] = XOR_i mul_table(coeff[r,i])[inputs[i]]."""
        rows, k = coeff.shape
        assert k == len(inputs)
        out = []
        for r in range(rows):
            acc = np.zeros_like(inputs[0])
            for i in range(k):
                c = int(coeff[r, i])
                if c == 0:
                    continue
                elif c == 1:
                    acc ^= inputs[i]
                else:
                    acc ^= gf.mul_table(c)[inputs[i]]
            out.append(acc)
        return out

    # -- batched API (stripe-batch engine, ec/batch.py) -------------------

    def transform_batch(self, coeff: np.ndarray,
                        block: np.ndarray) -> np.ndarray:
        """Apply a (rows, k) coefficient matrix to a (B, k, L) window
        block in ONE vectorized call -> (B, rows, L).

        The GF(256) transform is independent per byte column, so the
        batch dim is free: the numpy path runs each table lookup over
        the whole (B, L) plane at once (one numpy op per coefficient
        for the entire block), while the native AVX2 kernel walks the
        contiguous per-window row views — window-sized streams stay
        L2-resident, which measures ~2x faster than flattening the
        batch into one long per-position stream. Either way the block
        costs one engine dispatch, and the bytes are identical to B
        separate per-window transforms."""
        coeff = np.asarray(coeff, np.uint8)
        block = np.ascontiguousarray(block, dtype=np.uint8)
        bsz, k, n = block.shape
        rows = coeff.shape[0]
        assert k == coeff.shape[1], (coeff.shape, block.shape)
        if self.use_native and bsz and n:
            from ..native import gf256 as _native
            out = np.empty((bsz, rows, n), np.uint8)
            for b in range(bsz):
                for r, row in enumerate(_native.transform(
                        coeff, [block[b, i] for i in range(k)])):
                    out[b, r] = row
            return out
        outs = self._apply_numpy(coeff, [block[:, i, :] for i in range(k)])
        return np.stack(outs, axis=1)

    def encode_batch(self, block: np.ndarray) -> np.ndarray:
        """(B, k, L) data windows -> (B, k+m, L) full shard windows."""
        block = np.asarray(block, np.uint8)
        parity = self.transform_batch(self.parity, block)
        return np.concatenate([block, parity], axis=1)

    def verify_batch(self, block: np.ndarray) -> np.ndarray:
        """(B, k+m, L) stored windows -> (B,) bool verdicts, one
        parity recompute dispatch for the whole block."""
        block = np.asarray(block, np.uint8)
        par = self.transform_batch(self.parity, block[:, :self.k, :])
        return (par == block[:, self.k:, :]).all(axis=(1, 2))

    def reconstruct_batch(self, present_rows: list[int],
                          want_rows: list[int],
                          block: np.ndarray) -> np.ndarray:
        """Rebuild want_rows for every window of a (B, k, L) block of
        present shards (stacked in present_rows order) -> (B, r, L)."""
        coeff = gf.cached_shard_rows(tuple(want_rows),
                                     tuple(present_rows), self.k, self.n)
        return self.transform_batch(coeff, block)

    # -- public API -------------------------------------------------------

    def encode(self, shards: list[np.ndarray | bytes | None]) -> list[np.ndarray]:
        """Compute parity from shards[:k]; returns a fresh list of k+m
        writable arrays (any parity entries passed in are ignored)."""
        data = [np.frombuffer(s, dtype=np.uint8).copy()
                if isinstance(s, (bytes, bytearray, memoryview))
                else np.asarray(s, dtype=np.uint8) for s in shards[:self.k]]
        parity = self._apply(self.parity, data)
        return data + parity

    def verify(self, shards) -> bool:
        """The unified backend verify: accepts a list of k+m equal-length
        rows OR a stacked (k+m, L) uint8 array (every backend answers
        the same `verify(block) -> bool` — EcVolume.verify_window no
        longer branches per encoder type)."""
        if len(shards) != self.n:
            return False
        data = [np.ascontiguousarray(s, dtype=np.uint8)
                for s in shards[:self.k]]
        parity = self._apply(self.parity, data)
        for got, want in zip(shards[self.k:], parity):
            if not np.array_equal(np.asarray(got, dtype=np.uint8), want):
                return False
        return True

    def reconstruct(self, shards: list[np.ndarray | None],
                    data_only: bool = False) -> list[np.ndarray]:
        """Rebuild missing (None) shards in place semantics; returns full list.

        Needs >= k present shards (reference guard:
        command_ec_rebuild.go:110 treats <10 as unrepairable).
        """
        present = [i for i, s in enumerate(shards) if s is not None]
        missing = [i for i, s in enumerate(shards) if s is None]
        if not missing:
            return [np.asarray(s, dtype=np.uint8) for s in shards]
        if len(present) < self.k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {self.k}")
        use = present[:self.k]
        if data_only:
            missing = [i for i in missing if i < self.k]
        inputs = [np.asarray(shards[i], dtype=np.uint8) for i in use]
        coeff = gf.shard_rows(missing, use, self.k, self.n)
        rebuilt = self._apply(coeff, inputs)
        out = [None if s is None else np.asarray(s, dtype=np.uint8)
               for s in shards]
        for idx, row in zip(missing, rebuilt):
            out[idx] = row
        return out

    def reconstruct_data(self, shards: list[np.ndarray | None]) -> list[np.ndarray]:
        """Rebuild only the k data shards (reference: ReconstructData,
        store_ec.go:322 degraded-read path)."""
        return self.reconstruct(shards, data_only=True)
