"""JAX RS(10,4) encoder — the TPU-native GF(256) shard transform.

Re-expresses the reference's klauspost/reedsolomon Encode/Reconstruct
(amd64 PSHUFB assembly, called at ec_encoder.go:192,264 and store_ec.go:322)
as a jittable bitplane transform:

    gf_mul(c, x) = XOR_{j in bits(x)} gf_mul(c, 1 << j)

so a (rows, k) GF(256) coefficient matrix applied to k shard byte-streams
becomes, for each output row, an accumulation of AND/XOR over the 8
bitplanes of each input shard — pure uint8 VPU ops with no gathers, no
data-dependent control flow, and static shapes. XLA fuses the whole
transform into a few elementwise loops; the Pallas kernel in
ops/gf256_pallas.py implements the same math with explicit HBM->VMEM
double-buffering for peak bandwidth.

The coefficient matrix is a *constant* under jit (closed over, shaped
(rows, k, 8) by gf.bitplane_constants), so each distinct transform —
encode's (4,10) parity map or a particular reconstruction's (r,10) map —
compiles once and is cached.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf


def _apply_bitplanes(consts: np.ndarray, data: jax.Array) -> jax.Array:
    """out[..., r, :] = XOR_i gf_mul(coeff[r, i], data[..., i, :]).

    consts: (rows, k, 8) uint8 bitplane constants (host numpy, becomes a
            compile-time constant).
    data:   (..., k, n) uint8 shard bytes.
    returns (..., rows, n) uint8.
    """
    rows, k, _ = consts.shape
    out = []
    for r in range(rows):
        acc = None
        for i in range(k):
            row = consts[r, i]
            if not row.any():
                continue
            x = data[..., i, :]
            term = None
            for j in range(8):
                cj = int(row[j])
                if cj == 0:
                    continue
                # 0x00/0xFF mask of bit j of every byte of shard i
                mask = ((x >> j) & 1) * jnp.uint8(0xFF)
                t = mask & jnp.uint8(cj)
                term = t if term is None else term ^ t
            if term is None:
                continue
            acc = term if acc is None else acc ^ term
        out.append(acc if acc is not None
                   else jnp.zeros(data.shape[:-2] + (data.shape[-1],), jnp.uint8))
    return jnp.stack(out, axis=-2)


@functools.lru_cache(maxsize=256)
def _compiled_transform(coeff_key: bytes, rows: int, k: int, use_pallas: bool):
    """jit-compiled transform for a fixed coefficient matrix."""
    coeff = np.frombuffer(coeff_key, dtype=np.uint8).reshape(rows, k)
    consts = gf.bitplane_constants(coeff)

    if use_pallas:
        from ..ops.gf256_pallas import gf256_matmul_pallas

        @jax.jit
        def fn(data):
            return gf256_matmul_pallas(consts, data)
    else:
        @jax.jit
        def fn(data):
            return _apply_bitplanes(consts, data)
    return fn


@functools.lru_cache(maxsize=256)
def _compiled_batch_transform(coeff_key: bytes, rows: int, k: int):
    """jit of the VMAPPED bitplane transform for a fixed coefficient
    matrix — compiled once per (rows, k) coefficient shape (jit's own
    shape cache then holds one executable per (B, L) block shape).
    The stripe-batch engine's device path: one dispatch carries a whole
    (B, k, L) window block, and with the block sharded along the batch
    dim XLA partitions the elementwise bitplane loops across devices
    with zero cross-device traffic (the transform is per-window)."""
    coeff = np.frombuffer(coeff_key, dtype=np.uint8).reshape(rows, k)
    consts = gf.bitplane_constants(coeff)
    return jax.jit(jax.vmap(lambda d: _apply_bitplanes(consts, d)))


@functools.lru_cache(maxsize=8)
def _batch_sharding(ndev: int):
    """NamedSharding(P('batch')) over all attached devices (SNIPPETS.md
    [1] pattern); None when a single device makes sharding moot."""
    if ndev <= 1:
        return None
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()), ("batch",))
    return NamedSharding(mesh, P("batch"))


def shard_along_batch(block):
    """Place a (B, ...) block on the attached devices, sharded along
    the leading (batch) dim when >1 device is attached and B divides
    evenly; replicated single-device placement otherwise."""
    sharding = _batch_sharding(jax.device_count())
    if sharding is not None and block.shape[0] % jax.device_count() == 0:
        return jax.device_put(block, sharding)
    return jnp.asarray(block)


def _default_use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def apply_transform(coeff: np.ndarray, data: jax.Array,
                    use_pallas: bool | None = None) -> jax.Array:
    """Apply a GF(256) coefficient matrix to shard data on-device."""
    coeff = np.ascontiguousarray(coeff, dtype=np.uint8)
    rows, k = coeff.shape
    if use_pallas is None:
        use_pallas = _default_use_pallas()
    fn = _compiled_transform(coeff.tobytes(), rows, k, bool(use_pallas))
    return fn(data)


class JaxEncoder:
    """Drop-in for CpuEncoder with device-resident math.

    Accepts shard arrays shaped (k, n) or batched (..., k, n); returns
    jnp arrays. Bytes in, bytes out at the pipeline level is handled by
    the callers in ec/pipeline.py.
    """

    def __init__(self, data_shards: int = gf.DATA_SHARDS,
                 parity_shards: int = gf.PARITY_SHARDS,
                 use_pallas: bool | None = None):
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        self.use_pallas = use_pallas
        self.parity_coeff = gf.parity_matrix(self.k, self.n)

    # -- batched API (stripe-batch engine, ec/batch.py) -------------------

    def transform_batch(self, coeff: np.ndarray, block) -> jax.Array:
        """Apply a (rows, k) coefficient matrix to a (B, k, L) window
        block as ONE vmapped device dispatch -> (B, rows, L).

        Dispatch is asynchronous (jax) — the caller reads back via
        np.asarray when it actually needs the bytes, which is what lets
        the engine overlap block N+1's preads with block N's kernel.
        On the Pallas path the batch folds into the byte axis instead
        (the transform is columnwise, and the explicit-DMA kernel
        already tiles the stream); the vmapped XLA path is the one that
        shards along the batch dim on a multi-device mesh."""
        coeff = np.ascontiguousarray(coeff, dtype=np.uint8)
        rows, k = coeff.shape
        use_pallas = self.use_pallas
        if use_pallas is None:
            use_pallas = _default_use_pallas()
        if use_pallas:
            block = jnp.asarray(block, jnp.uint8)
            bsz, k2, n = block.shape
            flat = block.transpose(1, 0, 2).reshape(k2, bsz * n)
            out = apply_transform(coeff, flat, True)
            return out.reshape(rows, bsz, n).transpose(1, 0, 2)
        fn = _compiled_batch_transform(coeff.tobytes(), rows, k)
        return fn(shard_along_batch(np.asarray(block, np.uint8)))

    def encode_batch(self, block) -> jax.Array:
        """(B, k, L) data windows -> (B, k+m, L) full shard windows."""
        block = jnp.asarray(block, jnp.uint8)
        parity = self.transform_batch(self.parity_coeff, block)
        return jnp.concatenate([block, parity], axis=1)

    def verify_batch(self, block) -> np.ndarray:
        """(B, k+m, L) stored windows -> (B,) bool verdicts; the parity
        recompute AND the comparison both run on device, one dispatch."""
        block = jnp.asarray(block, jnp.uint8)
        par = self.transform_batch(self.parity_coeff,
                                   block[:, :self.k, :])
        return np.asarray((par == block[:, self.k:, :]).all(axis=(1, 2)))

    def reconstruct_batch(self, present_rows: list[int],
                          want_rows: list[int], block) -> jax.Array:
        """Rebuild want_rows for every window of a (B, k, L) block of
        present shards (stacked in present_rows order) -> (B, r, L)."""
        coeff = gf.cached_shard_rows(tuple(want_rows),
                                     tuple(present_rows), self.k, self.n)
        return self.transform_batch(coeff, block)

    # data: (..., k, n) -> parity (..., m, n)
    def parity(self, data: jax.Array) -> jax.Array:
        return apply_transform(self.parity_coeff, data, self.use_pallas)

    def encode(self, data: jax.Array) -> jax.Array:
        """(..., k, n) data -> (..., k+m, n) full shard set."""
        data = jnp.asarray(data, jnp.uint8)
        return jnp.concatenate([data, self.parity(data)], axis=-2)

    def verify(self, shards) -> bool:
        """The unified backend verify: accepts a list of k+m equal-length
        rows OR a stacked (..., k+m, L) array — the same
        `verify(block) -> bool` signature as CpuEncoder."""
        shards = jnp.asarray(np.asarray(shards, np.uint8))
        par = self.parity(shards[..., :self.k, :])
        return bool(jnp.array_equal(par, shards[..., self.k:, :]))

    def reconstruct_rows(self, present_rows: list[int], shards: jax.Array,
                         want_rows: list[int]) -> jax.Array:
        """Rebuild want_rows from the k rows listed in present_rows.

        shards: (..., k, n) — the present shards stacked in present_rows
        order. The (len(want), k) coefficient matrix is inverted on host
        (tiny) exactly like reedsolomon.Reconstruct does before its matmul.
        """
        coeff = gf.shard_rows(list(want_rows), list(present_rows),
                              self.k, self.n)
        return apply_transform(coeff, jnp.asarray(shards, jnp.uint8),
                               self.use_pallas)

    def reconstruct(self, shards: list, data_only: bool = False) -> list:
        """List-of-(n,)-arrays-or-None API matching CpuEncoder.reconstruct."""
        present = [i for i, s in enumerate(shards) if s is not None]
        missing = [i for i, s in enumerate(shards) if s is None]
        if len(present) < self.k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {self.k}")
        if data_only:
            missing = [i for i in missing if i < self.k]
        out = [None if s is None else np.asarray(s, dtype=np.uint8)
               for s in shards]
        if not missing:
            return out
        use = present[:self.k]
        stacked = jnp.stack([jnp.asarray(np.asarray(shards[i], np.uint8))
                             for i in use], axis=0)
        rebuilt = np.asarray(self.reconstruct_rows(use, stacked, missing))
        for row, idx in enumerate(missing):
            out[idx] = rebuilt[row]
        return out

    def reconstruct_data(self, shards: list) -> list:
        return self.reconstruct(shards, data_only=True)
