"""EC stripe layout math: map (offset, size) in the logical volume to
shard-local intervals.

Layout (reference: ec_encoder.go:16-22, ec_locate.go:11-83): the logical
.dat byte stream is laid out row-major into DATA_SHARDS=10 columns — first
as rows of 10 x large blocks (1GB), then rows of 10 x small blocks (1MB)
for the tail. Shard file i holds column i: its large blocks in row order,
then its small blocks. Any (offset, size) maps to a list of
(shard_id, shard_offset, length) intervals by pure arithmetic — this is
the "sequence parallel" layout of the storage world, and the shape the TPU
mesh shards batches of volumes over.
"""

from __future__ import annotations

from dataclasses import dataclass

from .gf import DATA_SHARDS

LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024         # 1MB


@dataclass(frozen=True)
class Interval:
    block_index: int          # column-major index within its block area
    inner_offset: int
    size: int
    is_large_block: bool
    large_block_rows: int

    def to_shard_and_offset(self, large_block: int = LARGE_BLOCK_SIZE,
                            small_block: int = SMALL_BLOCK_SIZE
                            ) -> tuple[int, int]:
        """(shard_id, offset within shard file) — ec_locate.go:73-83."""
        off = self.inner_offset
        row = self.block_index // DATA_SHARDS
        if self.is_large_block:
            off += row * large_block
        else:
            off += self.large_block_rows * large_block + row * small_block
        return self.block_index % DATA_SHARDS, off


def check_blocks(large_block: int, small_block: int) -> None:
    """Geometry guard: the padded dat size reconstructed from a shard
    file (10 * shard_size) only lands in the same large-row count as the
    true size when large_block is a whole number of small blocks —
    reject configurations where reads could resolve wrong offsets."""
    if large_block <= 0 or small_block <= 0 \
            or large_block % small_block:
        raise ValueError(
            f"EC geometry: large block ({large_block}) must be a "
            f"positive multiple of the small block ({small_block})")


def n_large_block_rows(large_block: int, dat_size: int) -> int:
    """Number of full large rows the ENCODER writes — the
    strictly-greater loop at ec_encoder.go:208 (`for remaining >
    largeRow`), so an exact large-row multiple is laid out entirely as
    small rows. The reference's READ side uses two different formulas
    (dat_size/row at ec_locate.go:52, and a +10*small adjustment at
    :15) that disagree with its own encoder when dat_size falls within
    10*small below (or exactly at) a large-row multiple — reads in that
    window resolve to wrong shard offsets. Every path here shares the
    encoder's count instead."""
    if dat_size <= 0:
        return 0
    return (dat_size - 1) // (large_block * DATA_SHARDS)


def locate_offset(large_block: int, small_block: int, dat_size: int,
                  offset: int) -> tuple[int, bool, int]:
    """-> (block_index, is_large_block, inner_offset) — ec_locate.go:50-66."""
    large_row = large_block * DATA_SHARDS
    n_large_rows = n_large_block_rows(large_block, dat_size)
    if offset < n_large_rows * large_row:
        return offset // large_block, True, offset % large_block
    offset -= n_large_rows * large_row
    return offset // small_block, False, offset % small_block


def locate_data(large_block: int, small_block: int, dat_size: int,
                offset: int, size: int) -> list[Interval]:
    """Split (offset, size) into per-block intervals — ec_locate.go:11-48."""
    block_index, is_large, inner = locate_offset(
        large_block, small_block, dat_size, offset)
    n_large_rows = n_large_block_rows(large_block, dat_size)
    out: list[Interval] = []
    while size > 0:
        block_len = large_block if is_large else small_block
        remaining = block_len - inner
        take = min(size, remaining)
        out.append(Interval(block_index, inner, take, is_large, n_large_rows))
        size -= take
        if size == 0:
            return out
        block_index += 1
        if is_large and block_index == n_large_rows * DATA_SHARDS:
            is_large = False
            block_index = 0
        inner = 0
    return out


def shard_file_size(dat_size: int, large_block: int = LARGE_BLOCK_SIZE,
                    small_block: int = SMALL_BLOCK_SIZE) -> int:
    """Size of each shard file for a given logical volume size.

    Mirrors the encode loop (ec_encoder.go:204-225): full large rows while
    remaining > one large row, then small rows (zero-padded) for the tail.
    """
    large_row = large_block * DATA_SHARDS
    small_row = small_block * DATA_SHARDS
    n_large_rows = n_large_block_rows(large_block, dat_size)
    remaining = dat_size - n_large_rows * large_row
    n_small_rows = -(-remaining // small_row) if remaining > 0 else 0
    return n_large_rows * large_block + n_small_rows * small_block
