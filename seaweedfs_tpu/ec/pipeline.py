"""EC file pipelines: volume <-> 14 shard files, driven by the TPU encoder.

Reference workflow (ec_encoder.go):
  WriteEcFiles (:53)        .dat -> .ec00...ec13, streaming row batches
  WriteSortedFileFromIdx(:26) .idx -> .ecx sorted index
  RebuildEcFiles (:57)      regenerate missing shard files from >=10 present
  ec_decoder.go WriteDatFile(:150) shards -> .dat (ec.decode)

The reference streams 256KB x 10 buffers through an AVX2 encoder; here each
row batch is a host->HBM transfer and one kernel launch, so the dispatch
unit is much larger: 1 MB windows gathered eight at a time by the
stripe-batch engine (ec/batch.py) — 8 MB per shard per dispatch, the same
DMA-bound payload as the pre-batching 8 MB buffer, at an in-flight block
the resident-byte budget can hold.
"""

from __future__ import annotations

import os
import queue
import threading

import numpy as np

from ..storage import types as t
from ..storage.needle_map import walk_index_blob, write_sorted_index
from . import gf
from .batch import (DEFAULT_BATCH_WINDOWS, add_stat, clamp_batch_windows,
                    transform_block_async, window_blocks)
from .locate import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE

# read-ahead / dispatch-ahead depth of the threaded encode pipeline: 2 is
# enough to overlap file reads, host<->device transfer + kernel time, and
# file writes (classic double buffering); more just holds memory
_PIPE_DEPTH = 2


def to_ext(shard_id: int) -> str:
    return ".ec%02d" % shard_id


def get_encoder(backend: str = "auto"):
    """backend: 'tpu' | 'cpu' | 'auto' (tpu if a TPU is attached).

    The BASELINE `-ec.backend` switch: the volume CLI's -ecBackend flag
    (exported as SWTPU_EC_BACKEND) overrides 'auto', so an operator can
    pin the CPU path on TPU hosts or fail fast when the TPU is absent."""
    if backend == "auto":
        backend = os.environ.get("SWTPU_EC_BACKEND", "auto").lower()
    if backend not in ("auto", "tpu", "cpu"):
        raise ValueError(
            f"unknown EC backend {backend!r}: use auto | tpu | cpu")
    if backend == "tpu":
        # an explicit pin fails fast instead of silently degrading to
        # XLA-on-CPU when the accelerator is absent
        import jax
        if jax.default_backend() != "tpu":
            raise RuntimeError(
                "EC backend pinned to 'tpu' but no TPU is attached "
                f"(jax backend: {jax.default_backend()})")
    if backend == "auto":
        try:
            import jax
            backend = "tpu" if jax.default_backend() == "tpu" else "cpu"
        except Exception:
            backend = "cpu"
    if backend == "tpu":
        from .encoder_jax import JaxEncoder
        return JaxEncoder()
    from .encoder_cpu import CpuEncoder
    return CpuEncoder()


def _transform_buffers_async(encoder, coeff: np.ndarray,
                             buffers: list[np.ndarray]):
    """Launch the GF transform and return a thunk that yields the output
    byte buffers when called.

    On the JAX path the device work is dispatched asynchronously — the
    thunk blocks on readback, so the caller can overlap the NEXT batch's
    file reads and transfers with this batch's kernel time (the reference
    overlaps nothing: its 256KB loop at ec_encoder.go:114-186 is serial).
    CPU encoders compute eagerly and the thunk is a no-op."""
    if _use_overlap(encoder):  # the single async-dispatch predicate
        import os

        import jax
        from ..ops.gf256_pallas import (bytes_to_words, gf256_words_transform,
                                        words_to_bytes)
        n = len(buffers[0])
        words = [jax.device_put(bytes_to_words(b)) for b in buffers]
        if os.environ.get("SWTPU_EC_METHOD") == "mxu":
            # MXU GF(2) bit-matrix formulation (ops/gf256_mxu.py); the
            # default VPU Pallas kernel wins at small sizes, the MXU at
            # large streams — bench.py races both
            from ..ops.gf256_mxu import mxu_words_transform
            outs = mxu_words_transform(np.asarray(coeff, np.uint8), words)
        else:
            consts = gf.bitplane_constants(coeff)
            outs = gf256_words_transform(consts, words)
        return lambda: [words_to_bytes(np.asarray(o), n).copy()
                        for o in outs]
    # CPU path: native AVX2 kernel when built, numpy table lookup otherwise
    from .encoder_cpu import CpuEncoder
    if isinstance(encoder, CpuEncoder):
        out = encoder._apply(np.asarray(coeff, np.uint8),
                             [np.asarray(b, np.uint8) for b in buffers])
    else:
        out = CpuEncoder._apply_numpy(np.asarray(coeff, np.uint8),
                                      [np.asarray(b, np.uint8)
                                       for b in buffers])
    return lambda: out


def _transform_buffers(encoder, coeff: np.ndarray,
                       buffers: list[np.ndarray]) -> list[np.ndarray]:
    """Apply a GF coefficient matrix to equal-length host byte buffers."""
    return _transform_buffers_async(encoder, coeff, buffers)()


def _iter_row_batches(dat_size: int, large_block: int, small_block: int,
                      buffer_size: int):
    """Yield (start, block_size, buf_size, batch_index) specs covering the
    volume in row order (encodeData/encodeDataOneBatch split,
    ec_encoder.go:114-186)."""
    remaining = dat_size
    processed = 0
    large_row = large_block * gf.DATA_SHARDS
    while remaining > large_row:
        buf = min(buffer_size, large_block)
        assert large_block % buf == 0, (large_block, buf)
        for b in range(large_block // buf):
            yield processed, large_block, buf, b
        processed += large_row
        remaining -= large_row
    while remaining > 0:
        buf = min(buffer_size, small_block)
        assert small_block % buf == 0, (small_block, buf)
        for b in range(small_block // buf):
            yield processed, small_block, buf, b
        processed += small_block * gf.DATA_SHARDS
        remaining -= small_block * gf.DATA_SHARDS


def _use_overlap(encoder) -> bool:
    """Thread-overlap pays only when launch() is genuinely asynchronous
    (JAX dispatch returns before the device finishes). For host encoders
    the transform is eager, so the threads just add queue hand-off and
    GIL contention — measured 2x SLOWER on a single-core host — and the
    plain serial loop wins.

    This is THE async-dispatch predicate: _transform_buffers_async
    branches on it too, so the pipeline shape and the launch semantics
    cannot diverge."""
    try:
        from .encoder_jax import JaxEncoder
    except ImportError:  # jax-less host: CPU encoders only, eager
        return False
    return isinstance(encoder, JaxEncoder)


def _run_overlapped(read_batches, launch, write_result,
                    overlap: bool = True) -> None:
    """Three-stage threaded pipeline: a reader thread fills a bounded
    queue of input batches, the caller thread launches the (async) device
    transform, and a writer thread blocks on readback + file writes.

    With JAX async dispatch this overlaps file reads, host->device
    transfer + kernel time, and file writes — the fix for the fully
    serial round-3 pipeline (SURVEY §7 hard-part #1). Queue depth
    _PIPE_DEPTH bounds in-flight memory to ~2 batches.

    read_batches: generator yielding input batch objects.
    launch(batch) -> (batch, thunk) launched work.
    write_result(batch, thunk): called in writer-thread order.
    overlap=False degrades to the serial loop (host encoders).
    """
    if not overlap:
        for batch in read_batches:
            write_result(*launch(batch))
        return
    q_read: queue.Queue = queue.Queue(maxsize=_PIPE_DEPTH)
    q_write: queue.Queue = queue.Queue(maxsize=_PIPE_DEPTH)
    errs: list[BaseException] = []

    def reader() -> None:
        try:
            for batch in read_batches:
                if errs:
                    break
                q_read.put(batch)
        except BaseException as e:  # noqa: BLE001 — propagated below
            errs.append(e)
        finally:
            q_read.put(None)

    def writer() -> None:
        draining = False
        while True:
            item = q_write.get()
            if item is None:
                return
            if draining:
                continue
            try:
                write_result(*item)
            except BaseException as e:  # noqa: BLE001 — propagated below
                errs.append(e)
                draining = True  # keep consuming so the caller never blocks

    rt = threading.Thread(target=reader, daemon=True)
    wt = threading.Thread(target=writer, daemon=True)
    rt.start()
    wt.start()
    try:
        while True:
            batch = q_read.get()
            if batch is None:
                break
            if errs:
                continue  # drain reader output without launching more
            try:
                q_write.put(launch(batch))
            except BaseException as e:  # noqa: BLE001
                errs.append(e)
    finally:
        q_write.put(None)
        rt.join()
        wt.join()
    if errs:
        raise errs[0]


def encode_volume(base_name: str, encoder=None,
                  large_block: int = LARGE_BLOCK_SIZE,
                  small_block: int = SMALL_BLOCK_SIZE,
                  buffer_size: int = 1024 * 1024,
                  batch_windows: int = DEFAULT_BATCH_WINDOWS,
                  stats: dict | None = None) -> None:
    """Stripe <base>.dat into <base>.ec00 .. .ec13 (WriteEcFiles) with
    the stripe-batch engine: up to `batch_windows` stripe windows
    gather into one (B, 10, buf) block and ONE transform dispatch
    emits all four parity rows for every window in the block —
    ceil(W/B) dispatches per uniform-window run instead of W
    (ec/batch.py). A volume has at most two such runs: groups flush
    once at the large->small block-size boundary (mixed window
    lengths never share a block), so the whole-volume count is
    bounded by ceil(W_large/B) + ceil(W_small/B) — the exact
    ceil(W/B) the bench gates on holds when the buffer size divides
    both areas into equal windows (its geometry).

    Windows whose preads are contiguous in the .dat (consecutive
    buffers of the same block row) coalesce into one read per shard
    position, so the batch cuts syscalls the same ratio it cuts
    dispatches. File I/O still overlaps the device transform via the
    double-buffered reader/writer threads (`_run_overlapped`);
    `stats` accumulates the deterministic accounting
    (windows/batches/dispatches/preads/bytes) tools/bench_ec.py
    gates on."""
    encoder = encoder or get_encoder()
    parity = gf.parity_matrix()
    dat_path = base_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    outs = [open(base_name + to_ext(i), "wb") for i in range(gf.TOTAL_SHARDS)]
    f = open(dat_path, "rb")

    def groups():
        pending: list[tuple] = []
        limit = 1
        for spec in _iter_row_batches(dat_size, large_block, small_block,
                                      buffer_size):
            if pending and (spec[2] != pending[0][2]
                            or len(pending) >= limit):
                yield pending
                pending = []
            if not pending:
                # resident budget: data rows + parity rows per window
                limit = clamp_batch_windows(batch_windows, spec[2],
                                            gf.TOTAL_SHARDS)
            pending.append(spec)
        if pending:
            yield pending

    def read_block(group):
        """One (B, 10, buf) block read straight into its final array;
        contiguous window reads coalesce into single preads per shard
        position (no second joined-bytes copy is kept alive)."""
        buf = group[0][2]
        block = np.empty((len(group), gf.DATA_SHARDS, buf), np.uint8)
        preads = 0
        for i in range(gf.DATA_SHARDS):
            runs: list[list[int]] = []
            for start, bs, _, b in group:
                off = start + bs * i + b * buf
                if runs and off == runs[-1][0] + runs[-1][1]:
                    runs[-1][1] += buf
                else:
                    runs.append([off, buf])
            w = 0
            for off, ln in runs:
                f.seek(off)
                raw = f.read(ln)
                if len(raw) < ln:
                    raw += b"\x00" * (ln - len(raw))
                n = ln // buf
                block[w:w + n, i, :] = np.frombuffer(
                    raw, np.uint8).reshape(n, buf)
                w += n
            preads += len(runs)
        return block, preads

    def batches():
        for group in groups():
            yield read_block(group)

    def launch(item):
        block, preads = item
        add_stat(stats, preads=preads, bytes_read=int(block.nbytes))
        thunk = transform_block_async(encoder, parity, block, stats)
        try:
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.EC_ENCODE_BYTES.inc(int(block.nbytes))
        except ImportError:
            pass
        return item, thunk

    def write_result(item, thunk):
        block, _ = item
        parities = thunk()      # (B, m, buf)
        for i in range(gf.DATA_SHARDS):
            outs[i].write(np.ascontiguousarray(block[:, i, :]).tobytes())
        for p in range(gf.PARITY_SHARDS):
            outs[gf.DATA_SHARDS + p].write(
                np.ascontiguousarray(parities[:, p, :]).tobytes())

    try:
        _run_overlapped(batches(), launch, write_result,
                        overlap=_use_overlap(encoder))
    finally:
        f.close()
        for o in outs:
            o.close()


def write_ec_files(base_name: str, encoder=None,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   buffer_size: int = 1024 * 1024,
                   batch_windows: int = DEFAULT_BATCH_WINDOWS,
                   stats: dict | None = None) -> None:
    """Historical name for `encode_volume` (WriteEcFiles) — same
    batched engine, byte-identical shard files at any batch size."""
    encode_volume(base_name, encoder=encoder, large_block=large_block,
                  small_block=small_block, buffer_size=buffer_size,
                  batch_windows=batch_windows, stats=stats)


def write_ec_files_batched(base_names: list[str], encoder=None,
                           large_block: int = LARGE_BLOCK_SIZE,
                           small_block: int = SMALL_BLOCK_SIZE,
                           buffer_size: int = 8 * 1024 * 1024,
                           batch_volumes: int = 8) -> None:
    """Stripe SEVERAL volumes' .dat files with batched kernel launches —
    the rack-encode configuration (BASELINE.json 64x30GB; reference
    encodes volumes serially, command_ec_encode.go:89).

    The GF transform is independent per byte column, so equal-length
    buffer groups from DIFFERENT volumes concatenate into one stream per
    shard position: one kernel launch then carries up to
    batch_volumes x 10 x buffer_size bytes. This is the single-chip
    expression of parallel/mesh.py's "vol" axis; on a multi-chip mesh the
    same batch shards over devices.

    Parity buffers surface in flush order, not stream order, so every
    parity write lands at an explicitly recorded shard offset.
    """
    import collections

    encoder = encoder or get_encoder()
    parity = gf.parity_matrix()
    outs: dict[str, list] = {}
    # buf_len -> list of (data_buffers, base, parity_shard_offset)
    pending: dict[int, list] = {}
    pending_refs: dict[str, int] = {}   # base -> unflushed group count
    fully_enqueued: set[str] = set()
    # launched-but-unwritten kernel batches: lets the next group's file
    # reads overlap this group's device time (dispatch-ahead)
    inflight: collections.deque = collections.deque()

    def maybe_close(base: str) -> None:
        # bound open fds: at most batch_volumes in-flight volumes keep
        # their 14 shard files open (a 64-volume rack batch would
        # otherwise hold ~900 fds past the default 1024 soft limit)
        if base in fully_enqueued and pending_refs.get(base, 0) == 0:
            for f in outs.pop(base, []):
                f.close()

    def drain_one() -> None:
        group, thunk = inflight.popleft()
        parities = thunk()
        off = 0
        for buffers, base, shard_off in group:
            ln = len(buffers[0])
            for p, pbuf in enumerate(parities):
                f = outs[base][gf.DATA_SHARDS + p]
                f.seek(shard_off)
                f.write(np.asarray(pbuf[off:off + ln], np.uint8).tobytes())
            off += ln
            pending_refs[base] -= 1
            maybe_close(base)

    def flush(buf_len: int) -> None:
        group = pending.pop(buf_len, [])
        if not group:
            return
        cat = [np.concatenate([g[0][i] for g in group])
               if len(group) > 1 else group[0][0][i]
               for i in range(gf.DATA_SHARDS)]
        inflight.append(
            (group, _transform_buffers_async(encoder, parity, cat)))
        while len(inflight) > _PIPE_DEPTH:
            drain_one()

    try:
        for base in base_names:
            dat_path = base + ".dat"
            dat_size = os.path.getsize(dat_path)
            outs[base] = [open(base + to_ext(i), "wb")
                          for i in range(gf.TOTAL_SHARDS)]
            shard_pos = 0
            with open(dat_path, "rb") as f:
                for start, block_size, buf, b in _iter_row_batches(
                        dat_size, large_block, small_block, buffer_size):
                    buffers = []
                    for i in range(gf.DATA_SHARDS):
                        f.seek(start + block_size * i + b * buf)
                        raw = f.read(buf)
                        if len(raw) < buf:
                            raw += b"\x00" * (buf - len(raw))
                        buffers.append(np.frombuffer(raw, np.uint8))
                        outs[base][i].write(raw)
                    pending.setdefault(buf, []).append(
                        (buffers, base, shard_pos))
                    pending_refs[base] = pending_refs.get(base, 0) + 1
                    shard_pos += buf
                    if len(pending[buf]) >= batch_volumes:
                        flush(buf)
            fully_enqueued.add(base)
            maybe_close(base)
        for buf_len in list(pending):
            flush(buf_len)
        while inflight:
            drain_one()
    finally:
        for fs in outs.values():
            for f in fs:
                f.close()


def write_sorted_file_from_idx(base_name: str,
                               ext: str = ".ecx") -> None:
    """<base>.idx -> sorted <base>.ecx (WriteSortedFileFromIdx,
    ec_encoder.go:26-50). Tombstoned entries keep TOMBSTONE size."""
    with open(base_name + ".idx", "rb") as f:
        blob = f.read()
    entries = list(walk_index_blob(blob))
    write_sorted_index(entries, base_name + ext)


def present_shards(base_name: str) -> list[int]:
    return [i for i in range(gf.TOTAL_SHARDS)
            if os.path.exists(base_name + to_ext(i))]


def _rebuild_rows(base_name: str, encoder, targets: list[int],
                  use: list[int], buffer_size: int,
                  stats: dict | None,
                  batch_windows: int = DEFAULT_BATCH_WINDOWS) -> None:
    """Regenerate the `targets` shard files from the k `use` shards
    through the stripe-batch engine: up to `batch_windows` buffer
    windows gather into one (B, k, buf) block read with ONE pread per
    survivor, and ONE encoder dispatch emits ALL target rows for every
    window in the block (len(targets) x k coefficients) — ceil(W/B)
    dispatches per rebuild instead of W."""
    coeff = gf.cached_shard_rows(tuple(targets), tuple(use))
    shard_size = os.path.getsize(base_name + to_ext(use[0]))
    ins = [open(base_name + to_ext(i), "rb") for i in use]
    outs = [open(base_name + to_ext(i), "wb") for i in targets]
    n_windows = -(-shard_size // buffer_size) if shard_size else 0
    # resident budget: survivor rows in + target rows out per window
    batch_windows = clamp_batch_windows(batch_windows, buffer_size,
                                        len(use) + len(targets))

    def batches():
        for wi, count in window_blocks(n_windows, batch_windows):
            pos = wi * buffer_size
            take = min(count * buffer_size, shard_size - pos)
            rows = []
            for f in ins:
                f.seek(pos)
                raw = f.read(take)
                # zero-pad the tail to whole windows: GF of zero rows
                # is zero, and the pad is sliced off before writing
                if len(raw) < count * buffer_size:
                    raw += b"\x00" * (count * buffer_size - len(raw))
                rows.append(np.frombuffer(raw, np.uint8
                                          ).reshape(count, buffer_size))
            add_stat(stats, preads=len(ins), bytes_read=take * len(ins))
            yield np.stack(rows, axis=1), take

    def launch(item):
        block, take = item
        if stats is not None:
            stats["launches"] = stats.get("launches", 0) + 1
        return item, transform_block_async(encoder, coeff, block, stats)

    def write_result(item, thunk):
        block, take = item
        rebuilt = thunk()       # (B, targets, buf)
        for j, o in enumerate(outs):
            out = np.ascontiguousarray(rebuilt[:, j, :]
                                       ).tobytes()[:take]
            add_stat(stats, bytes_rebuilt=len(out))
            o.write(out)

    try:
        _run_overlapped(batches(), launch, write_result,
                        overlap=_use_overlap(encoder))
    finally:
        for f in ins:
            f.close()
        for o in outs:
            o.close()


def rebuild_ec_files(base_name: str, encoder=None,
                     buffer_size: int = 1024 * 1024,
                     sequential: bool = False,
                     stats: dict | None = None,
                     batch_windows: int = DEFAULT_BATCH_WINDOWS,
                     targets: "list[int] | None" = None,
                     use: "list[int] | None" = None) -> list[int]:
    """Regenerate missing shard files from >=10 present ones
    (RebuildEcFiles -> rebuildEcFiles, ec_encoder.go:227-281).
    Returns the rebuilt shard ids.

    Default is the stripe-batched whole-volume rebuild: ALL missing
    shards of the volume come out of one coefficient-matrix dispatch
    per `batch_windows`-window block — the survivors are read ONCE
    (one pread per survivor per block) and every lost row rides the
    same launch. `sequential=True` keeps the pre-batching per-shard
    shape (one full pass of survivor reads + one launch per window
    PER lost shard) as the baseline tools/bench_ec.py measures the
    batching win against; `stats` (optional dict) accumulates
    bytes_read / bytes_rebuilt / launches / dispatches / preads /
    windows / seconds for that repair-bandwidth accounting.

    `targets` restricts WHICH absent shards are regenerated (the
    rebuild-to-target admin route: a node rebuilding one shard it will
    host must not also materialize every other missing shard only to
    delete it again); None keeps the rebuild-everything default.
    `use` restricts WHICH present shards feed the reconstruction (the
    same route's validated clean-input set: the first-k-on-disk
    default could otherwise pick up a local shard the caller knows to
    be rotten); None keeps the first-k default."""
    import time as _time

    encoder = encoder or get_encoder()
    have = present_shards(base_name)
    missing = [i for i in range(gf.TOTAL_SHARDS) if i not in have]
    if targets is not None:
        absent = set(missing)
        bad = [t for t in targets if t not in absent]
        if bad:
            raise ValueError(
                f"rebuild targets {bad} already present on disk")
        missing = sorted(set(targets))
    if not missing:
        return []
    if use is not None:
        absent_use = [s for s in use if s not in have]
        if absent_use:
            raise ValueError(
                f"rebuild inputs {absent_use} not present on disk")
        have = sorted(set(use))
    if len(have) < gf.DATA_SHARDS:
        raise ValueError(
            f"unrepairable: only {len(have)} shards present, "
            f"need {gf.DATA_SHARDS}")
    use = have[:gf.DATA_SHARDS]
    t0 = _time.perf_counter()
    if sequential:
        for target in missing:
            _rebuild_rows(base_name, encoder, [target], use,
                          buffer_size, stats, batch_windows=1)
    else:
        _rebuild_rows(base_name, encoder, missing, use,
                      buffer_size, stats, batch_windows=batch_windows)
    if stats is not None:
        stats["seconds"] = stats.get("seconds", 0.0) + \
            (_time.perf_counter() - t0)
        stats["rebuilt"] = missing
    return missing


def write_dat_file(base_name: str, dat_size: int,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   buffer_size: int = 8 * 1024 * 1024) -> None:
    """Reassemble <base>.dat from the 10 data shard files (ec.decode;
    ec_decoder.go:150-191)."""
    from .locate import locate_data
    ins = []
    for i in range(gf.DATA_SHARDS):
        path = base_name + to_ext(i)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"data shard {i} missing; rebuild first: {path}")
        ins.append(open(path, "rb"))
    try:
        with open(base_name + ".dat", "wb") as out:
            pos = 0
            while pos < dat_size:
                take = min(buffer_size, dat_size - pos)
                for iv in locate_data(large_block, small_block, dat_size,
                                      pos, take):
                    sid, soff = iv.to_shard_and_offset(large_block,
                                                       small_block)
                    ins[sid].seek(soff)
                    out.write(ins[sid].read(iv.size))
                pos += take
    finally:
        for f in ins:
            f.close()


def write_idx_file_from_ec_index(base_name: str) -> None:
    """<base>.ecx (+ .ecj tombstone replay) -> <base>.idx
    (WriteIdxFileFromEcIndex, ec_decoder.go:17-42). Entries copy over
    as-is (tombstoned ones keep their TOMBSTONE size); any unfolded .ecj
    keys are appended as delete entries so the rebuilt needle map agrees
    with the EC delete journal."""
    from ..storage.needle_map import pack_entry
    with open(base_name + ".ecx", "rb") as f:
        # tombstoned .ecx entries keep their original offset (in-place
        # MarkNeedleDeleted), but the reassembled .dat is truncated to the
        # live extent (FindDatFileSize skips deletes) — rewrite them to
        # offset 0 like the reference's nm.Delete idx entries, or the
        # loaded volume's integrity check would see an index entry past
        # the data end
        entries = [(key, 0 if size == t.TOMBSTONE_FILE_SIZE else off, size)
                   for key, off, size in walk_index_blob(f.read())]
    ecj_path = base_name + ".ecj"
    if os.path.exists(ecj_path):
        with open(ecj_path, "rb") as f:
            j = f.read()
        for i in range(len(j) // 8):
            key = int.from_bytes(j[i * 8:(i + 1) * 8], "big")
            entries.append((key, 0, t.TOMBSTONE_FILE_SIZE))
    with open(base_name + ".idx", "wb") as f:
        for key, off, size in entries:
            f.write(pack_entry(key, off, size))


def find_dat_file_size(base_name: str,
                       version: int = t.CURRENT_VERSION) -> int:
    """Logical volume size from the .ecx index (FindDatFileSize,
    ec_decoder.go:47-69): max(offset + record length) over entries."""
    size = 8  # superblock
    with open(base_name + ".ecx", "rb") as f:
        blob = f.read()
    for key, off, esize in walk_index_blob(blob):
        if esize == t.TOMBSTONE_FILE_SIZE:
            continue
        end = off + t.actual_size(esize, version)
        size = max(size, end)
    return size
