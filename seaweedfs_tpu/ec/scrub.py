"""Paced background EC parity scrubber.

The silent-corruption detector the reference lacks: its integrity
checking stops at per-needle CRCs *on read* (needle/crc.go), so a
flipped bit in a cold shard is discovered only when a degraded read
finally needs that row — mid-recovery, when redundancy is already
spent. This scrubber walks every mounted EC volume window-by-window
through ``EcVolume.verify_window`` (the same GF(256) transform the
encoder uses) and reports corrupt windows BEFORE they cost data.

Three disciplines keep it invisible to the foreground data plane:

* **token-bucket byte budget** (``-scrub.mbps``): every window's
  14 shard-row reads are paid for before they happen, so sustained
  scrub I/O can never exceed the operator's budget;
* **pause-on-foreground-latency** (``-scrub.pausems``): the unified
  wire layer feeds every served request's duration into
  ``foreground`` (the exact feed the
  ``SeaweedFS_volumeServer_request_seconds`` histogram observes);
  when recent foreground latency crosses the threshold the scrubber
  parks until the data plane has been healthy for a full window —
  a loaded or struggling server is never scrubbed harder;
* **executor isolation**: the reads + parity recompute run off the
  event loop, so a scrub window never stalls in-flight requests.

Observability: ``SeaweedFS_scrub_*`` metrics, a ``scrub`` trace span
per volume pass, and ``/debug/scrub`` status (+ ``POST ?run=1`` to
force a cycle — how the soak drives it deterministically). The
``scrub.read`` failpoint (action ``flip``) plants corruption the
scrubber must find; see tools/soak.py's ``scrub`` scenario.
"""

from __future__ import annotations

import asyncio
import collections
import time

from ..util import events, glog, tracing
from . import gf

# how long the scrubber sleeps while parked behind hot foreground
# traffic before re-checking
_PAUSE_SLEEP_S = 0.25


class ForegroundLoad:
    """Recent-request latency window fed by wire.observe(), answering
    one question: has any foreground request in the last `window_s`
    been slower than `pause_ms`?

    Aggregated into per-second (count, max-duration) buckets, NOT a
    per-request ring: a request-count-bounded ring evicts its evidence
    fastest exactly when the server is busiest — at 500 req/s a 512-
    entry ring covers ~1 s and a 2 s-old slow outlier is already gone.
    One bucket per wall second covers the window regardless of rate.
    note() runs only on the event-loop thread (wire.observe inside
    async handlers); the scrubber reads on the same loop."""

    __slots__ = ("_buckets",)

    # bucket deque length bounds the largest usable window_s
    MAX_WINDOW_S = 32

    def __init__(self):
        self._buckets: collections.deque = collections.deque(
            maxlen=self.MAX_WINDOW_S)   # [sec, count, max_dur_s]

    def clear(self) -> None:
        self._buckets.clear()

    def note(self, dur_s: float) -> None:
        sec = int(time.monotonic())
        b = self._buckets[-1] if self._buckets else None
        if b is not None and b[0] == sec:
            b[1] += 1
            if dur_s > b[2]:
                b[2] = dur_s
        else:
            self._buckets.append([sec, 1, dur_s])

    def snapshot(self, window_s: float) -> tuple[int, float]:
        """(request count, max duration ms) over the last window_s."""
        # whole-second buckets: include any bucket that overlaps the
        # window (err on the pause side, never evict evidence early)
        cutoff = int(time.monotonic() - window_s)
        count, worst = 0, 0.0
        for sec, n, mx in reversed(self._buckets):
            if sec < cutoff:
                break
            count += n
            if mx > worst:
                worst = mx
        return count, worst * 1000.0

    def hot(self, pause_ms: float, window_s: float) -> bool:
        if pause_ms <= 0:
            return False
        _, worst_ms = self.snapshot(min(window_s, self.MAX_WINDOW_S))
        return worst_ms >= pause_ms


# module-level singleton: server/wire.py notes every served request
# here (one deque append on the hot path), the scrubber consults it
foreground = ForegroundLoad()


class TokenBucket:
    """Byte-budget pacing: consume(n) debits n bytes, sleeping until
    the refill (rate bytes/s, burst-capped) covers them. rate <= 0
    disables pacing. Injectable clock/sleep for deterministic tests."""

    def __init__(self, rate_bytes_s: float, burst_bytes: float | None = None,
                 now=time.monotonic, sleep=asyncio.sleep):
        self.rate = rate_bytes_s
        self.burst = burst_bytes if burst_bytes is not None \
            else max(rate_bytes_s, 1.0)
        self._now = now
        self._sleep = sleep
        self._tokens = self.burst
        self._last = now()

    def _refill(self) -> None:
        now = self._now()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    async def consume(self, n: int) -> float:
        """Debit n bytes; returns seconds slept."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        slept = 0.0
        # oversized requests (a window bigger than the burst) go
        # negative and simply earn back over time — a single huge
        # window must not deadlock the bucket
        if self._tokens < n:
            wait = (n - self._tokens) / self.rate
            await self._sleep(wait)
            slept = wait
            self._refill()
        self._tokens -= n
        return slept


class Scrubber:
    """Continuous paced parity scrub over a Store's mounted EC volumes.

    One instance per volume server (per -workers worker: each scrubs
    its own partition). `run()` is the long-lived background task —
    its handle is retained by the server and cancelled on stop (the
    weedlint orphan-task discipline for paced background loops);
    `run_cycle()` is one full pass, also callable via
    POST /debug/scrub?run=1."""

    # corruption reports kept for /debug/scrub (the full stream also
    # goes to glog.error and the corruptions counter)
    MAX_REPORTS = 64

    def __init__(self, store, mbps: float = 8.0,
                 interval_s: float = 300.0,
                 window_bytes: int = 4 << 20,
                 pause_ms: float = 50.0,
                 pause_window_s: float = 2.0,
                 load: ForegroundLoad | None = None):
        self.store = store
        self.mbps = mbps
        self.interval_s = interval_s
        self.window_bytes = window_bytes
        self.pause_ms = pause_ms
        self.pause_window_s = pause_window_s
        self.bucket = TokenBucket(mbps * (1 << 20))
        self.load = load if load is not None else foreground
        self.state = "idle"
        self.current: dict | None = None
        self.cycles = 0
        self.windows = 0
        self.corrupt_windows = 0
        self.bytes_scanned = 0
        self.pauses = 0          # pause EVENTS (not poll iterations)
        self.paused_s = 0.0      # total time parked behind foreground
        self.paced_sleep_s = 0.0
        # wall stamp for display, monotonic twin for the uptime DELTA
        # (an NTP step must not make uptime jump — the wall/monotonic
        # discipline every merged debug surface follows)
        self.started_at = time.time()
        self.started_mono = time.monotonic()
        self.corruptions: collections.deque = collections.deque(
            maxlen=self.MAX_REPORTS)
        self.last_cycle: dict | None = None
        self._cycle_lock = asyncio.Lock()

    # ---- metrics ----

    def _count(self, name: str, n: float = 1, label: str | None = None
               ) -> None:
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        c = getattr(metrics, name)
        (c.labels(label) if label is not None else c).inc(n)

    # ---- the long-lived paced loop ----

    async def run(self) -> None:
        # first pass starts after ONE pacing interval, not at boot:
        # a restarting fleet must not synchronize a scrub stampede
        # with its own recovery traffic
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.run_cycle()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the scrubber must
                # outlive any single cycle's failure shape, visibly
                glog.warning("scrub cycle failed: %s: %s",
                             type(e).__name__, e)

    async def run_cycle(self) -> dict:
        """One full pass over every mounted EC volume. Serialized:
        a manual POST ?run=1 racing the background loop must not
        double-scan (and double-charge the budget)."""
        async with self._cycle_lock:
            t0 = time.monotonic()
            report = {"volumes": 0, "windows": 0, "corrupt": 0,
                      "bytes": 0, "skipped": [], "errors": []}
            for vid in sorted(self.store.ec_volumes):
                ev = self.store.ec_volumes.get(vid)
                if ev is None:
                    continue  # unmounted while we scanned
                try:
                    await self._scrub_volume(vid, ev, report)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — one volume's
                    # failure (unmount race, dead holder) must not end
                    # the pass over the others
                    glog.warning("scrub vid=%d: %s: %s", vid,
                                 type(e).__name__, e)
                    report["errors"].append(
                        {"volume": vid, "error": str(e)})
            self.cycles += 1
            self._count("SCRUB_CYCLES")
            report["seconds"] = round(time.monotonic() - t0, 3)
            self.last_cycle = report
            self.state = "idle"
            self.current = None
            return report

    async def _scrub_volume(self, vid: int, ev, report: dict) -> None:
        if 0 not in ev.shards:
            # scrub ownership, decided FIRST because it is free (no
            # I/O): with shards spread across holders, every holder
            # scrubbing the full 14-row stripe would move the same
            # window bytes over the network once PER HOLDER per cycle
            # — and even the missing-shards probe below costs ~13
            # remote round trips per volume. Exactly one server scrubs
            # a volume: the holder of shard 0 (the lowest shard; a
            # volume whose shard 0 is LOST outright is skipped
            # everywhere — its stripe can't fully verify anyway).
            report["skipped"].append(
                {"volume": vid, "reason": "not-owner"})
            return
        ssize = await tracing.run_in_executor(lambda: ev.shard_size)
        missing = await tracing.run_in_executor(ev.missing_shards)
        if missing:
            # unreachable rows make the parity check inconclusive —
            # those shards verify via rebuild, not scrub
            report["skipped"].append(
                {"volume": vid, "missing_shards": missing})
            return
        report["volumes"] += 1
        with tracing.start_root("scrub", "volume", vid=vid) as sp:
            off = 0
            while off < ssize:
                w = min(self.window_bytes, ssize - off)
                nbytes = w * gf.TOTAL_SHARDS
                self.state = "scrubbing"
                self.current = {"volume": vid, "offset": off,
                                "shard_size": ssize}
                # pay for the window BEFORE reading it
                self.paced_sleep_s += await self.bucket.consume(nbytes)
                if self.load.hot(self.pause_ms, self.pause_window_s):
                    # one pause EVENT (however long the park lasts);
                    # paused_s carries the duration
                    self.state = "paused"
                    self.pauses += 1
                    self._count("SCRUB_PAUSES")
                    while self.load.hot(self.pause_ms,
                                        self.pause_window_s):
                        self.paused_s += _PAUSE_SLEEP_S
                        await asyncio.sleep(_PAUSE_SLEEP_S)
                self.state = "scrubbing"
                if self.store.ec_volumes.get(vid) is not ev:
                    sp.event("unmounted_midscrub")
                    return  # unmounted/remounted under us: stop here
                # strict: a row that would need RECONSTRUCTION mid-
                # window (holder died since the cycle's missing-shards
                # probe) raises instead of trivially verifying itself
                # — the volume lands in the cycle's errors, never in
                # its clean windows
                ok = await tracing.run_in_executor(
                    ev.verify_window, off, w, True)
                self.windows += 1
                self.bytes_scanned += nbytes
                report["windows"] += 1
                report["bytes"] += nbytes
                self._count("SCRUB_BYTES", nbytes)
                self._count("SCRUB_WINDOWS", 1,
                            "clean" if ok else "corrupt")
                if not ok:
                    self.corrupt_windows += 1
                    report["corrupt"] += 1
                    self._count("SCRUB_CORRUPTIONS")
                    rec = {"volume": vid, "offset": off, "size": w,
                           "wall": time.time()}
                    self.corruptions.append(rec)
                    sp.event("corrupt_window", offset=off, size=w)
                    events.record("scrub_corruption", vid=vid,
                                  offset=off, size=w)
                    glog.error(
                        "scrub: CORRUPT ec window vid=%d off=%d "
                        "size=%d — stored parity disagrees with "
                        "recomputed RS(10,4)", vid, off, w)
                off += w
            sp.nbytes = report["bytes"]

    # ---- /debug/scrub ----

    def status(self) -> dict:
        return {
            "enabled": self.interval_s > 0,
            "state": self.state,
            "current": self.current,
            "budget_mbps": self.mbps,
            "interval_s": self.interval_s,
            "window_bytes": self.window_bytes,
            "pause_ms": self.pause_ms,
            "cycles": self.cycles,
            "windows": self.windows,
            "corrupt_windows": self.corrupt_windows,
            "bytes_scanned": self.bytes_scanned,
            "pauses": self.pauses,
            "paused_s": round(self.paused_s, 3),
            "paced_sleep_s": round(self.paced_sleep_s, 3),
            "started_wall": round(self.started_at, 3),
            "uptime_s": round(time.monotonic() - self.started_mono, 1),
            "corruptions": list(self.corruptions),
            "last_cycle": self.last_cycle,
        }
