"""Paced background EC parity scrubber.

The silent-corruption detector the reference lacks: its integrity
checking stops at per-needle CRCs *on read* (needle/crc.go), so a
flipped bit in a cold shard is discovered only when a degraded read
finally needs that row — mid-recovery, when redundancy is already
spent. This scrubber walks every mounted EC volume in stripe-batched
window blocks through ``EcVolume.read_window_block`` + the batch
engine's ``verify_block`` (the same GF(256) transform the encoder
uses, ONE dispatch per ``-scrub.batch`` windows, block N+1's preads
overlapping block N's verify) and reports corrupt windows BEFORE they
cost data.

Three disciplines keep it invisible to the foreground data plane:

* **token-bucket byte budget** (``-scrub.mbps``): every window's
  14 shard-row reads are paid for before they happen, so sustained
  scrub I/O can never exceed the operator's budget;
* **pause-on-foreground-latency** (``-scrub.pausems``): the unified
  wire layer feeds every served request's duration into
  ``foreground`` (the exact feed the
  ``SeaweedFS_volumeServer_request_seconds`` histogram observes);
  when recent foreground latency crosses the threshold the scrubber
  parks until the data plane has been healthy for a full window —
  a loaded or struggling server is never scrubbed harder;
* **executor isolation**: the reads + parity recompute run off the
  event loop, so a scrub window never stalls in-flight requests.

Observability: ``SeaweedFS_scrub_*`` metrics, a ``scrub`` trace span
per volume pass, and ``/debug/scrub`` status (+ ``POST ?run=1`` to
force a cycle — how the soak drives it deterministically). The
``scrub.read`` failpoint (action ``flip``) plants corruption the
scrubber must find; see tools/soak.py's ``scrub`` scenario.
"""

from __future__ import annotations

import asyncio
import collections
import time

from ..util import events, glog, tracing
from . import gf
from .batch import (DEFAULT_BATCH_WINDOWS, clamp_batch_windows,
                    localize_corrupt_rows, verify_block)

# how long the scrubber sleeps while parked behind hot foreground
# traffic before re-checking
_PAUSE_SLEEP_S = 0.25


class ForegroundLoad:
    """Recent-request latency window fed by wire.observe(), answering
    one question: has any foreground request in the last `window_s`
    been slower than `pause_ms`?

    Aggregated into per-second (count, max-duration) buckets, NOT a
    per-request ring: a request-count-bounded ring evicts its evidence
    fastest exactly when the server is busiest — at 500 req/s a 512-
    entry ring covers ~1 s and a 2 s-old slow outlier is already gone.
    One bucket per wall second covers the window regardless of rate.
    note() runs only on the event-loop thread (wire.observe inside
    async handlers); the scrubber reads on the same loop."""

    __slots__ = ("_buckets",)

    # bucket deque length bounds the largest usable window_s
    MAX_WINDOW_S = 32

    def __init__(self):
        self._buckets: collections.deque = collections.deque(
            maxlen=self.MAX_WINDOW_S)   # [sec, count, max_dur_s]

    def clear(self) -> None:
        self._buckets.clear()

    def note(self, dur_s: float) -> None:
        sec = int(time.monotonic())
        b = self._buckets[-1] if self._buckets else None
        if b is not None and b[0] == sec:
            b[1] += 1
            if dur_s > b[2]:
                b[2] = dur_s
        else:
            self._buckets.append([sec, 1, dur_s])

    def snapshot(self, window_s: float) -> tuple[int, float]:
        """(request count, max duration ms) over the last window_s."""
        # whole-second buckets: include any bucket that overlaps the
        # window (err on the pause side, never evict evidence early)
        cutoff = int(time.monotonic() - window_s)
        count, worst = 0, 0.0
        for sec, n, mx in reversed(self._buckets):
            if sec < cutoff:
                break
            count += n
            if mx > worst:
                worst = mx
        return count, worst * 1000.0

    def hot(self, pause_ms: float, window_s: float) -> bool:
        if pause_ms <= 0:
            return False
        _, worst_ms = self.snapshot(min(window_s, self.MAX_WINDOW_S))
        return worst_ms >= pause_ms


# module-level singleton: server/wire.py notes every served request
# here (one deque append on the hot path), the scrubber consults it
foreground = ForegroundLoad()


class TokenBucket:
    """Byte-budget pacing: consume(n) debits n bytes, sleeping until
    the refill (rate bytes/s, burst-capped) covers them. rate <= 0
    disables pacing. Injectable clock/sleep for deterministic tests."""

    def __init__(self, rate_bytes_s: float, burst_bytes: float | None = None,
                 now=time.monotonic, sleep=asyncio.sleep):
        self.rate = rate_bytes_s
        self.burst = burst_bytes if burst_bytes is not None \
            else max(rate_bytes_s, 1.0)
        self._now = now
        self._sleep = sleep
        self._tokens = self.burst
        self._last = now()

    def _refill(self) -> None:
        now = self._now()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    async def consume(self, n: int) -> float:
        """Debit n bytes; returns seconds slept."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        slept = 0.0
        # oversized requests (a window bigger than the burst) go
        # negative and simply earn back over time — a single huge
        # window must not deadlock the bucket
        if self._tokens < n:
            wait = (n - self._tokens) / self.rate
            await self._sleep(wait)
            slept = wait
            self._refill()
        self._tokens -= n
        return slept


class Scrubber:
    """Continuous paced parity scrub over a Store's mounted EC volumes.

    One instance per volume server (per -workers worker: each scrubs
    its own partition). `run()` is the long-lived background task —
    its handle is retained by the server and cancelled on stop (the
    weedlint orphan-task discipline for paced background loops);
    `run_cycle()` is one full pass, also callable via
    POST /debug/scrub?run=1."""

    # corruption reports kept for /debug/scrub (the full stream also
    # goes to glog.error and the corruptions counter)
    MAX_REPORTS = 64

    # the scrubber's own block budget, TIGHTER than ec/batch.py's
    # memory ceiling: for a paced background scan the bound is the
    # per-block I/O *burst* the foreground shares a disk with, so it
    # stays at the historical single-window footprint (~56 MB)
    BLOCK_BYTES = 64 << 20

    def __init__(self, store, mbps: float = 8.0,
                 interval_s: float = 300.0,
                 window_bytes: int = 1 << 20,
                 pause_ms: float = 50.0,
                 pause_window_s: float = 2.0,
                 load: ForegroundLoad | None = None,
                 batch_windows: int | None = None):
        self.store = store
        self.mbps = mbps
        self.interval_s = interval_s
        # 1 MB stripe windows (was 4 MB pre-batching): with windows
        # batched per dispatch the smaller unit costs nothing extra and
        # localizes rot 4x finer; the per-block I/O burst stays at the
        # historical ~56 MB because of the block byte budget below
        self.window_bytes = window_bytes
        self.pause_ms = pause_ms
        self.pause_window_s = pause_window_s
        # stripe-batch width (-scrub.batch): windows verified per GF
        # transform dispatch; the token bucket pays and the foreground
        # pause gate runs per BLOCK, so a bigger batch trades pacing
        # granularity for dispatch amortisation (1 = pre-batching
        # shape). Clamped so one (B, 14, window) block stays inside
        # BLOCK_BYTES — a 4 MB-window scrub can never burst a 448 MB
        # block of reads however the flag is set.
        if batch_windows is None:
            batch_windows = DEFAULT_BATCH_WINDOWS
        self.batch_windows = clamp_batch_windows(
            max(1, batch_windows), window_bytes, gf.TOTAL_SHARDS,
            budget=self.BLOCK_BYTES)
        self.bucket = TokenBucket(mbps * (1 << 20))
        self.load = load if load is not None else foreground
        self.state = "idle"
        self.current: dict | None = None
        self.cycles = 0
        self.windows = 0
        self.batches = 0         # window blocks == GF transform
        #                          dispatches (one per block; surfaced
        #                          under both names in /debug/scrub)
        self.corrupt_windows = 0
        self.bytes_scanned = 0
        self.pauses = 0          # pause EVENTS (not poll iterations)
        self.paused_s = 0.0      # total time parked behind foreground
        self.paced_sleep_s = 0.0
        # wall stamp for display, monotonic twin for the uptime DELTA
        # (an NTP step must not make uptime jump — the wall/monotonic
        # discipline every merged debug surface follows)
        self.started_at = time.time()
        self.started_mono = time.monotonic()
        self.corruptions: collections.deque = collections.deque(
            maxlen=self.MAX_REPORTS)
        # machine-readable corruption reports for the autopilot
        # observer: (vid, window index/offset/size, LOCALIZED shard
        # ids) — structure, not prose. The same rows ride each cycle
        # report as `corrupt_windows` so a consumer can distinguish
        # fresh evidence from the cumulative ring.
        self.reported: collections.deque = collections.deque(
            maxlen=self.MAX_REPORTS)
        self.last_cycle: dict | None = None
        self._cycle_lock = asyncio.Lock()

    # ---- metrics ----

    def _count(self, name: str, n: float = 1, label: str | None = None
               ) -> None:
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        c = getattr(metrics, name)
        (c.labels(label) if label is not None else c).inc(n)

    # ---- the long-lived paced loop ----

    async def run(self) -> None:
        # first pass starts after ONE pacing interval, not at boot:
        # a restarting fleet must not synchronize a scrub stampede
        # with its own recovery traffic
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.run_cycle()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the scrubber must
                # outlive any single cycle's failure shape, visibly
                glog.warning("scrub cycle failed: %s: %s",
                             type(e).__name__, e)

    async def run_cycle(self) -> dict:
        """One full pass over every mounted EC volume. Serialized:
        a manual POST ?run=1 racing the background loop must not
        double-scan (and double-charge the budget)."""
        async with self._cycle_lock:
            t0 = time.monotonic()
            report = {"volumes": 0, "windows": 0, "batches": 0,
                      "dispatches": 0, "corrupt": 0,
                      "corrupt_windows": [],
                      "bytes": 0, "skipped": [], "errors": []}
            for vid in sorted(self.store.ec_volumes):
                ev = self.store.ec_volumes.get(vid)
                if ev is None:
                    continue  # unmounted while we scanned
                try:
                    await self._scrub_volume(vid, ev, report)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — one volume's
                    # failure (unmount race, dead holder) must not end
                    # the pass over the others
                    glog.warning("scrub vid=%d: %s: %s", vid,
                                 type(e).__name__, e)
                    report["errors"].append(
                        {"volume": vid, "error": str(e)})
            self.cycles += 1
            self._count("SCRUB_CYCLES")
            report["seconds"] = round(time.monotonic() - t0, 3)
            self.last_cycle = report
            self.state = "idle"
            self.current = None
            return report

    async def _scrub_volume(self, vid: int, ev, report: dict) -> None:
        if 0 not in ev.shards:
            # scrub ownership, decided FIRST because it is free (no
            # I/O): with shards spread across holders, every holder
            # scrubbing the full 14-row stripe would move the same
            # window bytes over the network once PER HOLDER per cycle
            # — and even the missing-shards probe below costs ~13
            # remote round trips per volume. Exactly one server scrubs
            # a volume: the holder of shard 0 (the lowest shard; a
            # volume whose shard 0 is LOST outright is skipped
            # everywhere — its stripe can't fully verify anyway).
            report["skipped"].append(
                {"volume": vid, "reason": "not-owner"})
            return
        ssize = await tracing.run_in_executor(lambda: ev.shard_size)
        missing = await tracing.run_in_executor(ev.missing_shards)
        if missing:
            # unreachable rows make the parity check inconclusive —
            # those shards verify via rebuild, not scrub
            report["skipped"].append(
                {"volume": vid, "missing_shards": missing})
            return
        report["volumes"] += 1
        n_windows = -(-ssize // self.window_bytes) if ssize else 0
        with tracing.start_root("scrub", "volume", vid=vid) as sp:
            # stripe-batched block loop with ONE-block read-ahead: the
            # preads of block N+1 (executor thread) overlap the GF
            # verify dispatch of block N (another executor thread) —
            # the scrub twin of the encode pipeline's double buffering.
            # Pacing discipline is preserved per BLOCK: every block's
            # bytes are paid for (token bucket) and the foreground
            # pause gate consulted BEFORE its reads are issued.
            wi = 0
            vol_bytes = 0
            self.current = None  # fresh volume: no stale position
            nxt = await self._pay_and_read(vid, ev, ssize, n_windows, 0) \
                if n_windows else None
            read_err: BaseException | None = None
            while nxt is not None and nxt != "unmounted":
                off, count, nbytes, block = nxt
                wi += count
                # `current` tracks the block being VERIFIED: the
                # read-ahead below must not advance the reported
                # position past windows whose verdicts aren't in yet
                # (a mid-cycle error would otherwise overstate
                # coverage by one block)
                self.current = {"volume": vid, "offset": off,
                                "shard_size": ssize, "windows": count}
                # encoder resolved INSIDE the executor thunk: first-use
                # lazy backend init (jax import, device probe) must
                # never block the event loop mid-cycle
                verify_task = asyncio.ensure_future(
                    tracing.run_in_executor(
                        lambda b=block, n=count * self.window_bytes:
                        verify_block(ev.encoder(n), b)))
                nxt, read_err = None, None
                if wi < n_windows:
                    try:
                        # prefetch block N+1 while N verifies
                        nxt = await self._pay_and_read(
                            vid, ev, ssize, n_windows, wi)
                    except asyncio.CancelledError:
                        verify_task.cancel()
                        raise
                    except Exception as e:  # noqa: BLE001 — re-raised
                        # below, AFTER block N's verdicts are counted
                        read_err = e
                oks = await verify_task
                self.batches += 1
                report["batches"] += 1
                report["dispatches"] += 1
                self._count("SCRUB_BATCHES")
                self.windows += count
                self.bytes_scanned += nbytes
                vol_bytes += nbytes
                report["windows"] += count
                report["bytes"] += nbytes
                self._count("SCRUB_BYTES", nbytes)
                for i, ok in enumerate(oks):
                    woff = off + i * self.window_bytes
                    w = min(self.window_bytes, ssize - woff)
                    self._count("SCRUB_WINDOWS", 1,
                                "clean" if ok else "corrupt")
                    if ok:
                        continue
                    self.corrupt_windows += 1
                    report["corrupt"] += 1
                    self._count("SCRUB_CORRUPTIONS")
                    # localize the rot to one shard row (hypothesis
                    # test over the block row slice we already hold):
                    # the structured report the autopilot repairs
                    # from. [] = ambiguous — the consumer must defer.
                    try:
                        # encoder resolved INSIDE the thunk, like the
                        # verify dispatch: lazy backend init must not
                        # block the event loop mid-cycle
                        sids = await tracing.run_in_executor(
                            lambda r=block[i]: localize_corrupt_rows(
                                ev.encoder(self.window_bytes), r))
                    except Exception as e:  # noqa: BLE001 —
                        # localization is advisory evidence; its
                        # failure must not hide the corruption itself
                        glog.warning("scrub localize vid=%d off=%d: "
                                     "%s", vid, woff, e)
                        sids = []
                    rec = {"volume": vid, "offset": woff, "size": w,
                           "wall": time.time()}
                    self.corruptions.append(rec)
                    struct = {"volume": vid,
                              "window": woff // self.window_bytes,
                              "offset": woff, "size": w,
                              "shards": sids, "wall": rec["wall"]}
                    self.reported.append(struct)
                    report["corrupt_windows"].append(struct)
                    sp.event("corrupt_window", offset=woff, size=w,
                             shards=sids)
                    events.record("scrub_corruption", vid=vid,
                                  offset=woff, size=w, shards=sids)
                    glog.error(
                        "scrub: CORRUPT ec window vid=%d off=%d "
                        "size=%d shards=%s — stored parity disagrees "
                        "with recomputed RS(10,4)", vid, woff, w,
                        sids or "unlocalized")
                if read_err is not None:
                    raise read_err
            if nxt == "unmounted":
                sp.event("unmounted_midscrub")
                return  # unmounted/remounted under us: stop here
            # THIS volume's bytes, not the cycle-cumulative report sum
            sp.nbytes = vol_bytes

    async def _pay_and_read(self, vid: int, ev, ssize: int,
                            n_windows: int, wi: int):
        """Token-bucket pay + foreground-pause gate + read ONE window
        block starting at window index `wi`. Returns (offset, count,
        real_bytes, block), or "unmounted" when the volume moved under
        us (checked after the gates, before any read I/O)."""
        count = min(self.batch_windows, n_windows - wi)
        off = wi * self.window_bytes
        nbytes = (min(ssize - off, count * self.window_bytes)
                  * gf.TOTAL_SHARDS)
        self.state = "scrubbing"
        if self.current is None:  # first block of a volume: nothing is
            # verifying yet, so progress points at what is being read
            self.current = {"volume": vid, "offset": off,
                            "shard_size": ssize, "windows": count}
        # pay for the block BEFORE reading it
        self.paced_sleep_s += await self.bucket.consume(nbytes)
        if self.load.hot(self.pause_ms, self.pause_window_s):
            # one pause EVENT (however long the park lasts);
            # paused_s carries the duration
            self.state = "paused"
            self.pauses += 1
            self._count("SCRUB_PAUSES")
            while self.load.hot(self.pause_ms, self.pause_window_s):
                self.paused_s += _PAUSE_SLEEP_S
                await asyncio.sleep(_PAUSE_SLEEP_S)
            self.state = "scrubbing"
        if self.store.ec_volumes.get(vid) is not ev:
            return "unmounted"
        # strict: a row that would need RECONSTRUCTION mid-cycle
        # (holder died since the cycle's missing-shards probe) raises
        # instead of trivially verifying itself — the volume lands in
        # the cycle's errors, never in its clean windows
        block = await tracing.run_in_executor(
            ev.read_window_block, off, count, self.window_bytes, True)
        return off, count, nbytes, block

    # ---- /debug/scrub ----

    def status(self) -> dict:
        return {
            "enabled": self.interval_s > 0,
            "state": self.state,
            "current": self.current,
            "budget_mbps": self.mbps,
            "interval_s": self.interval_s,
            "window_bytes": self.window_bytes,
            "pause_ms": self.pause_ms,
            "batch_windows": self.batch_windows,
            "cycles": self.cycles,
            "windows": self.windows,
            "batches": self.batches,
            "dispatches": self.batches,  # one GF dispatch per block
            "corrupt_windows": self.corrupt_windows,
            "bytes_scanned": self.bytes_scanned,
            "pauses": self.pauses,
            "paused_s": round(self.paused_s, 3),
            "paced_sleep_s": round(self.paced_sleep_s, 3),
            "started_wall": round(self.started_at, 3),
            "uptime_s": round(time.monotonic() - self.started_mono, 1),
            "corruptions": list(self.corruptions),
            "reported_windows": list(self.reported),
            "last_cycle": self.last_cycle,
        }
