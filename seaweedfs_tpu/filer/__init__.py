"""filer subpackage."""
