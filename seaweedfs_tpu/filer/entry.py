"""Filer entries: path -> attributes + chunk list.

Reference: weed/filer2/entry.go, entry_codec.go (pb-encoded attrs+chunks);
here entries serialize to JSON dicts for the embedded stores.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .filechunks import FileChunk, total_size


@dataclass
class Attr:
    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    replication: str = ""
    collection: str = ""
    ttl_sec: int = 0

    @property
    def is_directory(self) -> bool:
        return bool(self.mode & 0o40000)


@dataclass
class Entry:
    full_path: str
    attr: Attr = field(default_factory=Attr)
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict[str, str] = field(default_factory=dict)

    @property
    def is_directory(self) -> bool:
        return self.attr.is_directory

    @property
    def name(self) -> str:
        return self.full_path.rstrip("/").rsplit("/", 1)[-1]

    @property
    def dir_path(self) -> str:
        p = self.full_path.rstrip("/").rsplit("/", 1)[0]
        return p or "/"

    @property
    def size(self) -> int:
        return total_size(self.chunks)

    def to_dict(self) -> dict:
        return {
            "full_path": self.full_path,
            "attr": {
                "mtime": self.attr.mtime, "crtime": self.attr.crtime,
                "mode": self.attr.mode, "uid": self.attr.uid,
                "gid": self.attr.gid, "mime": self.attr.mime,
                "replication": self.attr.replication,
                "collection": self.attr.collection,
                "ttl_sec": self.attr.ttl_sec,
            },
            "chunks": [c.to_dict() for c in self.chunks],
            "extended": self.extended,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Entry":
        a = dict(d.get("attr", {}))
        known = {f for f in Attr.__dataclass_fields__}
        return cls(
            full_path=d["full_path"],
            attr=Attr(**{k: v for k, v in a.items() if k in known}),
            chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
            extended=d.get("extended", {}),
        )


def new_directory_entry(path: str, mode: int = 0o770) -> Entry:
    now = time.time()
    return Entry(full_path=path,
                 attr=Attr(mtime=now, crtime=now, mode=mode | 0o40000))
