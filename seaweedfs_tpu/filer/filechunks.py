"""Chunk overlay algebra: resolve overwrites among a file's chunk list.

Reference: weed/filer2/filechunks.go:121-222. Entries hold []FileChunk
(fid, offset, size, mtime); later-mtime chunks overwrite earlier byte
ranges. NonOverlappingVisibleIntervals folds chunks (sorted by mtime) into
a sorted list of visible intervals; ViewFromChunks clips that to a read
range, yielding (fid, offset-in-chunk, size, logical-offset) views.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class FileChunk:
    file_id: str
    offset: int
    size: int
    mtime: int  # monotonically increasing per overwrite (ns)
    etag: str = ""

    def to_dict(self) -> dict:
        return {"file_id": self.file_id, "offset": self.offset,
                "size": self.size, "mtime": self.mtime, "etag": self.etag}

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(file_id=d["file_id"], offset=d["offset"], size=d["size"],
                   mtime=d["mtime"], etag=d.get("etag", ""))


@dataclass(frozen=True)
class VisibleInterval:
    start: int
    stop: int
    file_id: str
    mtime: int
    chunk_offset: int = 0  # where this interval starts inside its chunk
    is_full_chunk: bool = False


@dataclass(frozen=True)
class ChunkView:
    file_id: str
    offset: int       # start within the stored chunk blob
    size: int
    logic_offset: int  # position in the logical file
    is_full_chunk: bool = False


def total_size(chunks: list[FileChunk]) -> int:
    """Max covered extent (filechunks.go TotalSize)."""
    return max((c.offset + c.size for c in chunks), default=0)


def etag(chunks: list[FileChunk]) -> str:
    if not chunks:
        return ""
    if len(chunks) == 1:
        return chunks[0].etag
    import hashlib
    h = hashlib.md5()
    for c in chunks:
        h.update(c.etag.encode())
    return h.hexdigest()


def non_overlapping_visible_intervals(
        chunks: list[FileChunk]) -> list[VisibleInterval]:
    """Fold chunks by mtime into sorted non-overlapping visible intervals
    (filechunks.go:181-199)."""
    visibles: list[VisibleInterval] = []
    for c in sorted(chunks, key=lambda x: x.mtime):
        new_stop = c.offset + c.size
        out: list[VisibleInterval] = []
        for v in visibles:
            if v.start < c.offset and c.offset < v.stop:
                out.append(VisibleInterval(
                    v.start, c.offset, v.file_id, v.mtime,
                    chunk_offset=v.chunk_offset, is_full_chunk=False))
            if v.start < new_stop and new_stop < v.stop:
                out.append(VisibleInterval(
                    new_stop, v.stop, v.file_id, v.mtime,
                    chunk_offset=v.chunk_offset + (new_stop - v.start),
                    is_full_chunk=False))
            if new_stop <= v.start or v.stop <= c.offset:
                out.append(v)
        out.append(VisibleInterval(c.offset, new_stop, c.file_id, c.mtime,
                                   chunk_offset=0, is_full_chunk=True))
        out.sort(key=lambda v: v.start)
        visibles = out
    return visibles


def view_from_visibles(visibles: list[VisibleInterval], offset: int,
                       size: int) -> list[ChunkView]:
    """Clip visible intervals to [offset, offset+size)
    (filechunks.go:84-104)."""
    stop = offset + size
    views: list[ChunkView] = []
    for v in visibles:
        if offset >= stop:
            break
        if v.stop <= offset:
            continue
        # jump across a hole: sparse ranges read as zeros (the reference's
        # clip loop drops post-hole views — filechunks.go:89 — which loses
        # data on sparse files; assemblers here zero-fill instead)
        cur = max(offset, v.start)
        if cur >= stop:
            break
        end = min(v.stop, stop)
        views.append(ChunkView(
            file_id=v.file_id,
            offset=v.chunk_offset + (cur - v.start),
            size=end - cur,
            logic_offset=cur,
            is_full_chunk=(v.is_full_chunk and v.start == cur
                           and v.stop <= stop),
        ))
        offset = end
    return views


def view_from_chunks(chunks: list[FileChunk], offset: int,
                     size: int) -> list[ChunkView]:
    return view_from_visibles(
        non_overlapping_visible_intervals(chunks), offset, size)


def minus_chunks(a: list[FileChunk], b: list[FileChunk]) -> list[FileChunk]:
    """Chunks in a but not in b (by fid) — incremental replication diff
    (filechunks.go MinusChunks)."""
    b_ids = {c.file_id for c in b}
    return [c for c in a if c.file_id not in b_ids]
