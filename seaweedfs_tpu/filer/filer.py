"""Filer core: path metadata over a pluggable store.

Reference: weed/filer2/filer.go:26-174 (CreateEntry with recursive parent
mkdir + overwritten-chunk deletion), filer_delete_entry.go:11-116
(recursive delete, batched), filer_deletion.go (async volume-grouped chunk
deletes), filer_notify.go (meta change events).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ..util import glog
from .entry import Attr, Entry, new_directory_entry
from .filechunks import FileChunk, minus_chunks
from .filerstore import FilerStore, create_store


class FilerError(Exception):
    pass


class Filer:
    def __init__(self, store: FilerStore | str = "memory",
                 chunk_deleter: Callable[[list[str]], None] | None = None,
                 **store_kwargs):
        self.store = (store if isinstance(store, FilerStore)
                      else create_store(store, **store_kwargs))
        # async chunk GC: fids queue drained by a background worker
        # (filer_deletion.go:11-52)
        self._pending_chunk_deletes: list[str] = []
        self._lock = threading.Lock()
        self.chunk_deleter = chunk_deleter
        # meta event listeners (NotifyUpdateEvent, filer_notify.go:9-31)
        self.listeners: list[Callable[[Entry | None, Entry | None], None]] = []

    # ---- notifications ----

    def _notify(self, old: Entry | None, new: Entry | None) -> None:
        for fn in self.listeners:
            try:
                fn(old, new)
            except Exception as e:
                # a broken listener must not block the mutation, but a
                # replication sink silently missing events is data loss
                glog.warning("filer listener %s failed: %r",
                             getattr(fn, "__name__", fn), e)

    # ---- entry CRUD ----

    def create_entry(self, entry: Entry) -> None:
        """Insert + mkdir -p of parents + delete overwritten chunks
        (filer.go:75-174)."""
        self._ensure_parents(entry.dir_path)
        old = self.store.find_entry(entry.full_path)
        if old is not None and not old.is_directory and not entry.is_directory:
            dropped = minus_chunks(old.chunks, entry.chunks)
            if dropped:
                self.delete_chunks([c.file_id for c in dropped])
        if old is not None and old.is_directory and not entry.is_directory:
            raise FilerError(
                f"cannot overwrite directory {entry.full_path} with a file")
        self.store.insert_entry(entry)
        self._notify(old, entry)

    def _ensure_parents(self, dir_path: str) -> None:
        if dir_path in ("", "/"):
            return
        existing = self.store.find_entry(dir_path)
        if existing is not None:
            if not existing.is_directory:
                raise FilerError(f"{dir_path} is a file, not a directory")
            return
        parent = dir_path.rsplit("/", 1)[0] or "/"
        self._ensure_parents(parent)
        self.store.insert_entry(new_directory_entry(dir_path))

    def find_entry(self, path: str) -> Entry | None:
        if path == "/":
            return new_directory_entry("/")
        return self.store.find_entry(path.rstrip("/") or "/")

    def update_entry(self, old: Entry | None, entry: Entry) -> None:
        self.store.update_entry(entry)
        self._notify(old, entry)

    def list_directory_entries(self, dir_path: str, start_file: str = "",
                               inclusive: bool = False,
                               limit: int = 1024) -> list[Entry]:
        return self.store.list_directory_entries(
            dir_path.rstrip("/") or "/", start_file, inclusive, limit)

    def delete_entry(self, path: str, recursive: bool = False,
                     ignore_recursive_error: bool = False) -> None:
        """Recursive meta+data delete (filer_delete_entry.go:11-116)."""
        entry = self.find_entry(path)
        if entry is None:
            raise FilerError(f"not found: {path}")
        if entry.is_directory:
            limit = 1024
            while True:
                children = self.list_directory_entries(path, limit=limit)
                if not children:
                    break
                if not recursive:
                    raise FilerError(f"directory not empty: {path}")
                for child in children:
                    try:
                        self.delete_entry(child.full_path, recursive=True)
                    except FilerError:
                        if not ignore_recursive_error:
                            raise
                if len(children) < limit:
                    break
        if entry.chunks:
            self.delete_chunks([c.file_id for c in entry.chunks])
        self.store.delete_entry(entry.full_path)
        self._notify(entry, None)

    # ---- rename (filer_grpc_server_rename.go AtomicRenameEntry) ----

    def rename_entry(self, old_path: str, new_path: str) -> None:
        entry = self.find_entry(old_path)
        if entry is None:
            raise FilerError(f"not found: {old_path}")
        self._move_recursive(entry, new_path)

    def _move_recursive(self, entry: Entry, new_path: str) -> None:
        if entry.is_directory:
            children = self.list_directory_entries(entry.full_path,
                                                   limit=1 << 30)
        else:
            children = []
        new_entry = Entry(full_path=new_path, attr=entry.attr,
                          chunks=entry.chunks, extended=entry.extended)
        self.create_entry(new_entry)
        for child in children:
            self._move_recursive(child, f"{new_path}/{child.name}")
        self.store.delete_entry(entry.full_path)
        self._notify(entry, new_entry)

    # ---- chunk GC ----

    def delete_chunks(self, fids: list[str]) -> None:
        if self.chunk_deleter is not None:
            self.chunk_deleter(fids)
            return
        with self._lock:
            self._pending_chunk_deletes.extend(fids)

    def drain_pending_chunk_deletes(self) -> list[str]:
        with self._lock:
            out = self._pending_chunk_deletes[:]
            self._pending_chunk_deletes.clear()
            return out

    def close(self) -> None:
        self.store.close()
