"""FilerStore plugin contract + registry.

Reference: weed/filer2/filerstore.go:13-29 (the 8-store plugin interface)
and the blank-import registration pattern (server/filer_server.go:23-35).
Stores register themselves on import; unavailable backends (missing
drivers) simply don't register.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from .entry import Entry


class FilerStore(ABC):
    name: str = "abstract"

    @abstractmethod
    def insert_entry(self, entry: Entry) -> None: ...

    @abstractmethod
    def update_entry(self, entry: Entry) -> None: ...

    @abstractmethod
    def find_entry(self, path: str) -> Entry | None: ...

    @abstractmethod
    def delete_entry(self, path: str) -> None: ...

    @abstractmethod
    def delete_folder_children(self, path: str) -> None: ...

    @abstractmethod
    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]: ...

    def count_entries(self) -> int:
        """Total entries held (shard observability); -1 = unsupported."""
        return -1

    def begin_transaction(self):  # optional
        return None

    def commit_transaction(self):
        return None

    def rollback_transaction(self):
        return None

    def close(self) -> None:
        return None


_REGISTRY: dict[str, type[FilerStore]] = {}


def register_store(cls: type[FilerStore]) -> type[FilerStore]:
    _REGISTRY[cls.name] = cls
    return cls


def available_stores() -> list[str]:
    _load_builtin()
    return sorted(_REGISTRY)


def create_store(name: str, **kwargs) -> FilerStore:
    _load_builtin()
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown filer store {name!r}; available: {available_stores()}")
    return _REGISTRY[name](**kwargs)


def _load_builtin() -> None:
    from .stores import (abstract_sql_store, leveldb2_store,  # noqa: F401
                         leveldb_store, memory_store, sqlite_store)
    # driver-gated plugins (reference: mysql/postgres via abstract_sql —
    # registered inside abstract_sql_store when drivers import — plus
    # cassandra/redis/etcd below)
    for mod in ("redis_store", "etcd_store", "cassandra_store",
                "tikv_store"):
        try:
            __import__(f"seaweedfs_tpu.filer.stores.{mod}")
        except ImportError:
            pass
