"""Sharded filer metadata plane: prefix -> shard routing.

The filer was the last single-process tier: every S3/WebDAV/FUSE
metadata op funnelled through one event loop and one store file. This
module shards the namespace by directory prefix:

* ``ShardMap`` — the raft-committed routing table the master quorum
  owns (epoch + longest-prefix rules + shard ownership + in-flight
  move intents). Committed through the same log-ordered apply as the
  ``seq_reserve`` windows (master/election.py), so splits, moves and
  ownership changes are totally ordered and a deposed leader can never
  commit a conflicting map.
* ``apply_map_op`` — the pure map transition function the master's
  ``POST /cluster/shards`` handler runs before raft-committing the
  result under an epoch CAS.
* ``RouteCache`` — client-side cached map + owners learned from
  ``307 + X-Shard-Owner`` answers, folded in exactly like the learned-
  leader rotation in ``WeedClient._master_get``.
* ``ShardNode`` — the per-filer-process runtime: ownership
  enforcement, the paced online split executor, and the journaled
  two-phase cross-shard move (rename) with idempotent crash replay.

Reference seam: the per-shard store stays a pluggable ``FilerStore``
(filer2/filerstore.go) — each shard process owns its own instance.
"""

from __future__ import annotations

import asyncio
import json
import time

import aiohttp

from ..security import tls
from ..util import events, failpoints, glog
from .entry import Entry
from .filer import Filer, FilerError

# split migration batch size (entries per paced hop)
BATCH = 256
# how long a cached client-side map stays fresh
MAP_TTL_S = 2.0
MiB = 1 << 20


def norm_path(p: str) -> str:
    p = "/" + (p or "").strip("/")
    while "//" in p:
        p = p.replace("//", "/")
    return p


def covers(prefix: str, path: str) -> bool:
    """True when `path` sits at or under directory `prefix`."""
    if prefix == "/":
        return True
    return path == prefix or path.startswith(prefix + "/")


class ShardMap:
    """Epoch-versioned prefix->shard routing table (JSON round-trip).

    ``rules`` are ``[prefix, shard_id]`` pairs; routing picks the
    longest matching prefix (the root rule ``["/", 0]`` always
    exists). ``owners`` maps shard id -> filer address. ``moves``
    holds in-flight split/rename intents — the raft-committed journal
    the executors replay idempotently after a crash.
    """

    def __init__(self, epoch: int = 0,
                 rules: list | None = None,
                 owners: dict | None = None,
                 moves: list | None = None):
        self.epoch = epoch
        self.rules = [[norm_path(p), int(s)] for p, s in
                      (rules or [["/", 0]])]
        self.owners = {int(k): v for k, v in (owners or {}).items()}
        self.moves = list(moves or [])

    # -- routing -------------------------------------------------------

    def route(self, path: str) -> int:
        """Longest-prefix match; the root rule guarantees a hit."""
        path = norm_path(path)
        best, best_len = 0, -1
        for prefix, sid in self.rules:
            if covers(prefix, path) and len(prefix) > best_len:
                best, best_len = sid, len(prefix)
        return best

    def owner_url(self, sid: int) -> str:
        return self.owners.get(sid, "")

    def matched_prefix(self, path: str) -> str:
        path = norm_path(path)
        best = "/"
        for prefix, sid in self.rules:
            if covers(prefix, path) and len(prefix) > len(best):
                best = prefix
        return best

    def shards_under(self, dir_path: str) -> set[int]:
        """Shards owning rule prefixes strictly below `dir_path` —
        their local listings contribute entries (at least the stub
        directory chain) to a merged listing of `dir_path`."""
        d = norm_path(dir_path)
        out: set[int] = set()
        for prefix, sid in self.rules:
            if prefix != d and covers(d, prefix):
                out.add(sid)
        return out

    def move_covering(self, path: str) -> dict | None:
        """The in-flight intent whose subtree covers `path`, if any."""
        path = norm_path(path)
        for mv in self.moves:
            root = mv.get("prefix") or mv.get("src") or ""
            if root and covers(root, path):
                return mv
        return None

    def move_by_id(self, mid: str) -> dict | None:
        for mv in self.moves:
            if mv.get("id") == mid:
                return mv
        return None

    # -- serialization -------------------------------------------------

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "rules": self.rules,
                "owners": {str(k): v for k, v in self.owners.items()},
                "moves": self.moves}

    @classmethod
    def from_dict(cls, d: dict | None) -> "ShardMap":
        d = d or {}
        return cls(epoch=int(d.get("epoch", 0)),
                   rules=d.get("rules") or [["/", 0]],
                   owners=d.get("owners") or {},
                   moves=d.get("moves") or [])

    def copy(self) -> "ShardMap":
        return ShardMap.from_dict(json.loads(json.dumps(self.to_dict())))


def apply_map_op(m: ShardMap, op: dict) -> ShardMap:
    """Pure transition: current map + operator/executor op -> new map.

    The master runs this on its APPLIED map, then raft-commits the
    result under a ``base_epoch`` CAS (election.py), so two leaders —
    or one deposed leader — can never interleave conflicting maps.
    Raises ValueError on an invalid transition (rendered as a 400).
    """
    n = m.copy()
    kind = op.get("op", "")
    if kind == "register":
        sid = int(op["shard"])
        n.owners[sid] = str(op["url"])
    elif kind == "set":
        # bootstrap / test hook: replace rules+owners wholesale
        if op.get("rules"):
            n.rules = [[norm_path(p), int(s)] for p, s in op["rules"]]
        if op.get("owners"):
            n.owners = {int(k): v for k, v in op["owners"].items()}
        if not any(p == "/" for p, _ in n.rules):
            raise ValueError("shard map must keep a root rule")
    elif kind == "split_intent":
        prefix = norm_path(op["prefix"])
        to = int(op["to"])
        frm = n.route(prefix)
        if frm == to:
            raise ValueError(f"{prefix} already routes to shard {to}")
        mid = f"split:{prefix}"
        if n.move_by_id(mid) is not None:
            return n                      # idempotent re-submit
        if n.move_covering(prefix) is not None:
            raise ValueError(f"a move already covers {prefix}")
        n.moves.append({"id": mid, "kind": "split", "prefix": prefix,
                        "from": frm, "to": to, "state": "copy",
                        "by": str(op.get("by", ""))})
    elif kind == "rename_intent":
        src, dst = norm_path(op["src"]), norm_path(op["dst"])
        mid = f"rename:{src}:{dst}"
        if n.move_by_id(mid) is not None:
            return n                      # idempotent re-submit
        if n.move_covering(src) or n.move_covering(dst):
            raise ValueError(f"a move already covers {src} or {dst}")
        n.moves.append({"id": mid, "kind": "rename", "src": src,
                        "dst": dst, "from": n.route(src),
                        "to": n.route(dst), "state": "copy",
                        "by": str(op.get("by", ""))})
    elif kind == "commit_move":
        mv = n.move_by_id(op["id"])
        if mv is None:
            raise ValueError(f"no such move {op['id']!r}")
        if mv["state"] == "copy":
            mv["state"] = "cleanup"
            if mv["kind"] == "split":
                # the one-raft-apply flip: routing cuts over atomically
                prefix = mv["prefix"]
                n.rules = [r for r in n.rules if r[0] != prefix]
                n.rules.append([prefix, mv["to"]])
    elif kind == "move_done":
        mv = n.move_by_id(op["id"])
        if mv is None:
            return n                      # idempotent completion
        n.moves.remove(mv)
    elif kind == "abort_move":
        mv = n.move_by_id(op["id"])
        if mv is not None:
            if mv["state"] != "copy":
                raise ValueError("cannot abort past the routing flip")
            n.moves.remove(mv)
    else:
        raise ValueError(f"unknown shard map op {kind!r}")
    return n


class RouteCache:
    """Client-side shard map: fetched from the masters with a short
    TTL, with owners learned from ``307 + X-Shard-Owner`` hints folded
    in (the learned-leader rotation discipline — a hint from the
    server that actually knows beats a stale cached map)."""

    def __init__(self, master_url: str = ""):
        self.master_seeds = [s.strip() for s in master_url.split(",")
                             if s.strip()]
        self.map = ShardMap()
        self._fetched = 0.0
        # prefix -> owner address learned from redirect hints; beats
        # the cached map until a fresher epoch arrives
        self.learned: dict[str, str] = {}
        self.learned_hits = 0

    def learn(self, prefix: str, owner: str, epoch: int = 0) -> None:
        if not owner:
            return
        self.learned[norm_path(prefix or "/")] = owner
        if epoch > self.map.epoch:
            self._fetched = 0.0           # our map is stale: refetch

    def owner_for(self, path: str) -> str:
        """Best-known owner address for `path` (may be "")."""
        path = norm_path(path)
        best, best_len = "", -1
        for prefix, owner in self.learned.items():
            if covers(prefix, path) and len(prefix) > best_len:
                best, best_len = owner, len(prefix)
        if best:
            self.learned_hits += 1
            return best
        return self.map.owner_url(self.map.route(path))

    async def refresh(self, http: aiohttp.ClientSession,
                      force: bool = False) -> ShardMap:
        if not self.master_seeds or (
                not force
                and time.monotonic() - self._fetched < MAP_TTL_S):
            return self.map
        last: Exception | None = None
        for seed in list(self.master_seeds):
            try:
                # chaos site: the shard-map fetch is a routed hop like
                # any other — an armed fault must degrade to the
                # cached/learned owners, never wedge the caller
                await failpoints.fail("filer.shard.route")
                async with http.get(tls.url(seed, "/cluster/shards"),
                                    timeout=aiohttp.ClientTimeout(
                                        total=5)) as resp:
                    body = await resp.json()
                if "epoch" in body:
                    fresh = ShardMap.from_dict(body)
                    if fresh.epoch >= self.map.epoch:
                        self.map = fresh
                        # a fresher committed map supersedes hearsay
                        self.learned.clear()
                    self._fetched = time.monotonic()
                    return self.map
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError, ValueError) as e:
                last = e
        if last is not None:
            glog.V(1).infof("shard map refresh failed: %s", last)
        return self.map


class GatewayRouter:
    """Bucket/path-granular shard routing for the S3/WebDAV gateways.

    A sharded gateway fleet runs one gateway per filer shard, each
    embedding that shard's ``Filer``. The router answers, per
    namespace path, the SIBLING gateway that owns it (or "" when the
    request is ours) so the gateway middleware can bounce foreign
    requests with ``307 + X-Shard-Owner``."""

    def __init__(self, shard_id: int, master_url: str,
                 peers: dict[int, str]):
        self.shard_id = shard_id
        self.routes = RouteCache(master_url)
        self.peers = dict(peers)          # shard id -> gateway address
        self.redirects = 0

    async def foreign_owner(self, http: aiohttp.ClientSession,
                            filer_path: str) -> str:
        m = await self.routes.refresh(http)
        sid = m.route(filer_path)
        if sid == self.shard_id:
            return ""
        return self.peers.get(sid, "")

    def matched_prefix(self, filer_path: str) -> str:
        return self.routes.map.matched_prefix(filer_path)


def merge_entry_lists(pages: list[list[Entry]], start_file: str,
                      inclusive: bool, limit: int,
                      sources: list[int] | None = None,
                      prefer: "ShardMap | None" = None) -> list[Entry]:
    """K-way merge of per-shard listing pages: global name order,
    every entry exactly once. ``sources[i]`` is the shard id page ``i``
    came from; a duplicate full_path (the dual-write window of an
    in-flight move) keeps the copy from the page whose SOURCE shard
    the map routes the path to, so a half-migrated entry never shows
    twice — and never shows its stale pre-move copy."""
    by_name: dict[str, tuple[bool, Entry]] = {}
    for i, page in enumerate(pages):
        src = sources[i] if sources and i < len(sources) else -1
        for e in page:
            name = e.name
            if start_file:
                if inclusive and name < start_file:
                    continue
                if not inclusive and name <= start_file:
                    continue
            routed = (prefer is not None and src >= 0
                      and prefer.route(e.full_path) == src)
            cur = by_name.get(name)
            if cur is None or (routed and not cur[0]):
                by_name[name] = (routed, e)
    ordered = [by_name[k][1] for k in sorted(by_name)]
    return ordered[:limit]


class ShardNode:
    """Per-filer-process shard runtime.

    Holds this process's view of the committed map (refresh loop +
    post-commit adoption), makes the ownership-enforcement decisions
    for the HTTP handlers, and drives the two background state
    machines: the paced online split executor and the journaled
    two-phase cross-shard move. Both replay idempotently from the
    raft-committed intent after a SIGKILL at any step."""

    def __init__(self, server, shard_id: int, shard_of: int,
                 peers: dict[int, str] | None = None,
                 split_mbps: float = 8.0):
        self.server = server              # FilerServer
        self.shard_id = shard_id
        self.shard_of = shard_of
        self.static_peers = dict(peers or {})
        self.routes = RouteCache(server.master_url)
        self.counters = {"local": 0, "redirect": 0, "forward": 0,
                         "merge": 0, "ingest": 0, "moved": 0,
                         "replayed": 0}
        from ..ec.scrub import TokenBucket
        self.bucket = TokenBucket(split_mbps * MiB)
        from .. import qos
        arb = qos.arbiter()
        if arb is not None:
            # PR-15 bandwidth arbiter: split migration is background
            # traffic — it yields to foreground pressure like repair
            self.bucket = arb.adopt("shard_move", self.bucket)
        self._http: aiohttp.ClientSession | None = None
        self._tasks: list[asyncio.Task] = []
        self._executor_wake = asyncio.Event()
        self._move_lock = asyncio.Lock()

    # -- lifecycle -----------------------------------------------------

    @property
    def map(self) -> ShardMap:
        return self.routes.map

    async def start(self) -> None:
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=30))
        await self._register()
        await self.routes.refresh(self._http, force=True)
        self._tasks.append(asyncio.create_task(self._refresh_loop()))
        self._tasks.append(asyncio.create_task(self._executor_loop()))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 — a dying executor
                # must not mask server stop, but stays visible
                glog.V(1).infof("shard %d task exit: %s",
                                self.shard_id, e)
        if self._http is not None:
            await self._http.close()

    async def _register(self) -> None:
        """Announce this shard's address into the committed map."""
        for attempt in range(20):
            try:
                if await self._map_op({"op": "register",
                                       "shard": self.shard_id,
                                       "url": self.server.url}):
                    return
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError, ValueError):
                pass
            await asyncio.sleep(min(0.25 * (attempt + 1), 2.0))
        glog.warning("shard %d: could not register with master %s",
                     self.shard_id, self.server.master_url)

    async def _map_op(self, op: dict) -> bool:
        """POST one map transition to the master (leader-chased) and
        adopt the committed map from the reply."""
        op = dict(op, by=self.server.url)
        seeds = list(self.routes.master_seeds) or [""]
        for seed in seeds:
            if not seed:
                continue
            try:
                # chaos site: the executor's commit hop — an armed
                # fault (or SIGKILL between hops) leaves the intent in
                # the committed map for idempotent replay
                await failpoints.fail("filer.shard.move")
                async with self._http.post(
                        tls.url(seed, "/cluster/shards"), json=op,
                        timeout=aiohttp.ClientTimeout(total=10),
                        allow_redirects=True) as resp:
                    body = await resp.json()
                if resp.status == 200 and "map" in body:
                    fresh = ShardMap.from_dict(body["map"])
                    if fresh.epoch >= self.map.epoch:
                        self.routes.map = fresh
                        self.routes.learned.clear()
                        self._note_epoch()
                    return True
                if resp.status == 400:
                    raise ValueError(body.get("error", "bad map op"))
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                glog.V(1).infof("shard map op via %s failed: %s", seed, e)
        return False

    def _note_epoch(self) -> None:
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FILER_SHARD_EPOCH.set(self.map.epoch)

    async def adopt_epoch(self, epoch: int) -> None:
        """A peer advertised a committed epoch ahead of ours (the
        post-flip poke): adopt it now instead of waiting out the
        refresh interval — a curl-level client must not ping-pong
        307s between two half-adopted shards."""
        if epoch <= self.map.epoch:
            return
        await self.routes.refresh(self._http, force=True)
        self._note_epoch()
        self._executor_wake.set()

    async def _poke_target(self, mv: dict) -> None:
        """Best-effort epoch push to the move's target: the flip is
        committed on the master, but the target only polls — failures
        here are covered by its refresh loop within MAP_TTL_S/2."""
        to = int(mv["to"])
        owner = self.static_peers.get(to) or self.map.owner_url(to)
        if not owner:
            return
        try:
            await self._peer_json(owner, "POST",
                                  "/__api__/shard/ingest",
                                  payload={"entries": [],
                                           "move": mv["id"],
                                           "epoch": self.map.epoch})
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            glog.V(1).infof("shard %d: epoch poke to %s failed: %s",
                            self.shard_id, owner, e)

    async def _refresh_loop(self) -> None:
        while True:
            await asyncio.sleep(MAP_TTL_S / 2)
            try:
                before = self.map.epoch
                await self.routes.refresh(self._http)
                self._note_epoch()
                if self.map.epoch != before or self._pending_moves():
                    self._executor_wake.set()
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                glog.V(1).infof("shard %d map refresh: %s", self.shard_id, e)

    # -- enforcement decisions (handlers consult these) ----------------

    def is_local(self, path: str) -> bool:
        return self.map.route(path) == self.shard_id

    def redirect_headers(self, path: str) -> dict | None:
        """Build the 307 hint headers for a foreign path, or None when
        the owner is unknown (caller answers 503, never a wrong 404)."""
        sid = self.map.route(path)
        owner = (self.static_peers.get(sid)
                 or self.map.owner_url(sid))
        if not owner:
            return None
        self.counters["redirect"] += 1
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FILER_SHARD_REQUESTS.labels("redirect").inc()
        return {"X-Shard-Owner": owner,
                "X-Shard-Prefix": self.map.matched_prefix(path),
                "X-Shard-Epoch": str(self.map.epoch)}

    def double_read_source(self, path: str) -> str:
        """During the cleanup window of a move TO this shard, a local
        miss double-routes to the old owner (never 404): the routing
        flip committed before the source finished its final copy
        pass + tombstone."""
        mv = self.map.move_covering(path)
        if (mv is not None and mv["kind"] == "split"
                and mv["state"] == "cleanup"
                and int(mv["to"]) == self.shard_id):
            sid = int(mv["from"])
            return self.static_peers.get(sid) or self.map.owner_url(sid)
        return ""

    # -- remote metadata ops (frames where channels exist, HTTP else) --

    async def _peer_json(self, owner: str, method: str, path: str,
                         params: dict | None = None,
                         payload: dict | None = None) -> dict:
        """One routed metadata hop to a peer shard. Rides the frame
        fabric when a channel to the peer exists (WeedClient.frame_hub
        probes once and remembers a downgrade), falling back to the
        resilient HTTP session."""
        # chaos site: EVERY peer-shard metadata hop, framed or HTTP
        # (callers — merge fan-out, double-read, ingest push — all
        # funnel through here)
        await failpoints.fail("filer.shard.route")
        body = b"" if payload is None else json.dumps(payload).encode()
        client = self.server.client
        if client is not None:
            framed = await client._frame_json(
                owner, method, path, params=params,
                headers={"content-type": "application/json"},
                body=body, timeout=15.0)
            if framed is not None and framed[0] == 200:
                return framed[2]
        async with self._http.request(
                method, tls.url(owner, path), params=params,
                data=body or None,
                headers={"Content-Type": "application/json"},
                timeout=aiohttp.ClientTimeout(total=15)) as resp:
            got = await resp.json()
            if resp.status != 200:
                raise OSError(
                    f"shard peer {owner} {path}: "
                    f"{got.get('error', resp.status)}")
            return got

    async def forward_lookup(self, owner: str, path: str) -> dict | None:
        """Routed lookup on a peer shard (double-read / merge hops)."""
        self.counters["forward"] += 1
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FILER_SHARD_REQUESTS.labels("forward").inc()
        # chaos site: every routed read hop
        await failpoints.fail("filer.shard.route")
        try:
            return await self._peer_json(
                owner, "GET", "/__api__/lookup",
                params={"path": path, "local": "1"})
        except OSError:
            return None

    async def peer_list(self, owner: str, dir_path: str,
                        start_file: str, inclusive: bool,
                        limit: int) -> list[Entry]:
        """One peer shard's local page of a merged listing."""
        self.counters["forward"] += 1
        # chaos site: the merged-listing fan-out hop
        await failpoints.fail("filer.shard.route")
        body = await self._peer_json(
            owner, "GET", "/__api__/list",
            params={"path": dir_path, "startFile": start_file,
                    "inclusive": "true" if inclusive else "false",
                    "limit": str(limit), "local": "1"})
        return [_entry_from_json(d) for d in body.get("entries", [])]

    async def merged_list(self, dir_path: str, start_file: str,
                          inclusive: bool, limit: int) -> list[Entry]:
        """Listing of an owned directory merged across every shard
        holding a rule below it (exactly-once, global name order)."""
        fan = self.map.shards_under(dir_path)
        mv = self.map.move_covering(dir_path)
        if mv is not None and mv.get("kind") == "split":
            fan |= {int(mv["from"]), int(mv["to"])}
        fan.discard(self.shard_id)
        local = self.server.filer.list_directory_entries(
            dir_path, start_file, inclusive, limit)
        if not fan:
            return local
        self.counters["merge"] += 1
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FILER_SHARD_REQUESTS.labels("merge").inc()
        pages = [local]
        srcs = [self.shard_id]
        for sid in sorted(fan):
            owner = self.static_peers.get(sid) or self.map.owner_url(sid)
            if not owner:
                continue
            try:
                pages.append(await self.peer_list(
                    owner, dir_path, start_file, inclusive, limit))
                srcs.append(sid)
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                # a dead peer degrades the merge, visibly — callers
                # still get the local+reachable slice, never a 500
                glog.warning("merged list %s: shard %d (%s) "
                             "unreachable: %s", dir_path, sid, owner, e)
        return merge_entry_lists(pages, start_file, inclusive, limit,
                                 sources=srcs, prefer=self.map)

    async def ingest(self, entries: list[dict]) -> int:
        """Idempotent migration sink: insert entries into the LOCAL
        store, mtime-gated so a stale source copy never clobbers a
        write that already landed here post-flip."""
        n = 0
        filer = self.server.filer
        for d in entries:
            try:
                e = _entry_from_json(d)
                have = filer.find_entry(e.full_path)
                if have is not None and have.attr.mtime > e.attr.mtime:
                    continue
                filer.create_entry(e)
                n += 1
            except (FilerError, KeyError, ValueError) as err:
                glog.warning("shard ingest %r: %s",
                             d.get("FullPath", d.get("full_path")), err)
        self.counters["ingest"] += n
        return n

    async def cross_shard_rename(self, src: str, dst: str) -> None:
        """Journaled two-phase move, driven synchronously by the
        source shard's rename handler: raft-commit the intent, copy
        the subtree to the destination shard (paths rebased),
        raft-commit the flip, final catch-up + tombstone, done. A
        SIGKILL between ANY two steps leaves the committed intent for
        the executor loop to replay idempotently on restart."""
        src, dst = norm_path(src), norm_path(dst)
        if self.server.filer.find_entry(src) is None:
            raise ValueError(f"rename source {src} not found")
        if not await self._map_op({"op": "rename_intent",
                                   "src": src, "dst": dst}):
            raise OSError("could not raft-commit rename intent")
        mv = self.map.move_by_id(f"rename:{src}:{dst}")
        if mv is None:
            raise OSError("rename intent missing from committed map")
        async with self._move_lock:
            await self._drive(dict(mv))

    # -- the split / move executors ------------------------------------

    def _pending_moves(self) -> list[dict]:
        """Intents this shard executes: the SOURCE drives both kinds
        (it owns the entries being copied out)."""
        return [mv for mv in self.map.moves
                if int(mv.get("from", -1)) == self.shard_id]

    async def _executor_loop(self) -> None:
        while True:
            try:
                await asyncio.wait_for(self._executor_wake.wait(),
                                       timeout=MAP_TTL_S * 2)
            except asyncio.TimeoutError:
                pass
            self._executor_wake.clear()
            for mv in self._pending_moves():
                try:
                    async with self._move_lock:
                        await self._drive(dict(mv))
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError, ValueError) as e:
                    # the intent stays committed: the next wake (or a
                    # restarted process) replays it from its state
                    glog.warning("shard %d: move %s stalled: %s",
                                 self.shard_id, mv.get("id"), e)

    async def _drive(self, mv: dict) -> None:
        """Drive one intent to completion from whatever state the
        committed map says it is in (idempotent crash replay: every
        phase re-runs safely; tombstoning only ever starts after the
        copy-complete commit)."""
        mid, kind = mv["id"], mv["kind"]
        root = mv.get("prefix") or mv["src"]
        started = time.monotonic()
        if mv.get("state") == "copy":
            self.counters["replayed"] += 1
            copied = await self._copy_subtree(
                root, mv, dst_root=mv.get("dst"))
            if not await self._map_op({"op": "commit_move", "id": mid}):
                return                    # retry on next wake
            flip = dict(id=mid, phase="flip", shard=self.shard_id,
                        entries=copied,
                        seconds=round(time.monotonic() - started, 3))
            if kind == "split":
                events.record("shard_split", **flip)
            else:
                events.record("shard_move", **flip)
            await self._poke_target(mv)
            mv["state"] = "cleanup"
        if mv.get("state") == "cleanup":
            # final catch-up pass: anything written locally between the
            # last pass and the flip streams over before the tombstone
            await self._copy_subtree(root, mv, dst_root=mv.get("dst"))
            self._tombstone_subtree(root)
            if not await self._map_op({"op": "move_done", "id": mid}):
                return
            done = dict(id=mid, phase="done", shard=self.shard_id,
                        seconds=round(time.monotonic() - started, 3))
            if kind == "split":
                events.record("shard_split", **done)
            else:
                events.record("shard_move", **done)
            await self._poke_target(mv)

    def _walk_local(self, root: str) -> list[Entry]:
        """Depth-first local subtree snapshot (root entry included)."""
        filer = self.server.filer
        out: list[Entry] = []
        root_entry = filer.find_entry(root)
        if root_entry is not None and root != "/":
            out.append(root_entry)
        stack = [root]
        while stack:
            d = stack.pop()
            last = ""
            while True:
                page = filer.list_directory_entries(d, last, False, BATCH)
                if not page:
                    break
                for e in page:
                    out.append(e)
                    if e.is_directory:
                        stack.append(e.full_path)
                last = page[-1].name
                if len(page) < BATCH:
                    break
        return out

    async def _copy_subtree(self, root: str, mv: dict,
                            dst_root: str | None = None) -> int:
        """Stream the subtree at `root` to the intent's target shard
        in paced batches (token bucket — arbitrated background
        traffic, the 1309.0186 discipline). Rename intents rewrite the
        path prefix to `dst_root` on the way out."""
        to = int(mv["to"])
        owner = self.static_peers.get(to) or self.map.owner_url(to)
        if not owner:
            raise OSError(f"move {mv['id']}: shard {to} has no owner")
        entries = self._walk_local(root)
        sent = 0
        for i in range(0, len(entries), BATCH):
            batch = entries[i:i + BATCH]
            out = []
            for e in batch:
                d = _entry_to_json(e)
                if dst_root is not None:
                    d["FullPath"] = _rebase(e.full_path, root, dst_root)
                out.append(d)
            payload = {"entries": out, "move": mv["id"]}
            nbytes = sum(len(json.dumps(d)) for d in out)
            await self.bucket.consume(nbytes)
            # chaos site: every migration hop — a SIGKILL here leaves
            # the raft-committed intent to replay idempotently
            if mv["kind"] == "split":
                await failpoints.fail("filer.shard.split")
            else:
                await failpoints.fail("filer.shard.move")
            await self._peer_json(owner, "POST", "/__api__/shard/ingest",
                                  payload=payload)
            sent += len(out)
            self.counters["moved"] += len(out)
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.FILER_SHARD_MOVED.inc(len(out))
        return sent

    def _tombstone_subtree(self, root: str) -> None:
        """Drop the migrated subtree from the LOCAL store only —
        straight store deletes, so the moved entries' chunks are never
        queued for GC (they now belong to the target shard)."""
        store = self.server.filer.store
        store.delete_folder_children(root)
        if root != "/":
            store.delete_entry(root)
        # store-level deletes bypass the filer listeners: fence the
        # collapsed listings wholesale
        self.server.bump_gen_fence(root, subtree=True)

    # -- status --------------------------------------------------------

    def status(self) -> dict:
        entry_count = -1
        count = getattr(self.server.filer.store, "count_entries", None)
        if count is not None:
            entry_count = count()
        return {"shard": self.shard_id, "of": self.shard_of,
                "url": self.server.url, "epoch": self.map.epoch,
                "entries": entry_count, "rules": self.map.rules,
                "owners": {str(k): v
                           for k, v in self.map.owners.items()},
                "moves": self.map.moves, "counters": dict(self.counters)}


# -- entry JSON plumbing (the /__api__ wire shape) ---------------------

def _entry_to_json(e: Entry) -> dict:
    return {"FullPath": e.full_path, "Mtime": e.attr.mtime,
            "Crtime": e.attr.crtime, "Mode": e.attr.mode,
            "Uid": e.attr.uid, "Gid": e.attr.gid, "Mime": e.attr.mime,
            "Replication": e.attr.replication,
            "Collection": e.attr.collection, "TtlSec": e.attr.ttl_sec,
            "chunks": [c.to_dict() for c in e.chunks],
            "extended": e.extended}


def _entry_from_json(d: dict) -> Entry:
    from .entry import Attr
    from .filechunks import FileChunk
    return Entry(
        full_path=d["FullPath"],
        attr=Attr(mtime=d.get("Mtime", 0.0), crtime=d.get("Crtime", 0.0),
                  mode=d.get("Mode", 0o660), uid=d.get("Uid", 0),
                  gid=d.get("Gid", 0), mime=d.get("Mime", ""),
                  replication=d.get("Replication", ""),
                  collection=d.get("Collection", ""),
                  ttl_sec=d.get("TtlSec", 0)),
        chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
        extended=d.get("extended", {}))


def _rebase(path: str, old_root: str, new_root: str) -> str:
    if path == old_root:
        return new_root
    return new_root + path[len(old_root):]
