"""Embedded filer store plugins."""
