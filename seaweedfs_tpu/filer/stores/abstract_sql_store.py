"""Shared SQL store logic for the RDBMS-backed filer stores.

Reference: weed/filer2/abstract_sql/abstract_sql_store.go — one
`filemeta(dirhash, name, directory, meta)` table keyed by a 64-bit hash
of the parent directory plus the file name; mysql/ and postgres/ only
supply the connection + dialect. Here any DB-API 2.0 connection works
(sqlite3 in-tree; pymysql/psycopg2 when installed).
"""

from __future__ import annotations

import hashlib
import json
import threading

from ..entry import Entry
from ..filerstore import FilerStore, register_store


def dir_hash(dir_path: str) -> int:
    """Signed 64-bit hash of the parent directory (abstract_sql's
    util.HashStringToLong equivalent, md5-based)."""
    h = hashlib.md5((dir_path.rstrip("/") or "/").encode()).digest()
    v = int.from_bytes(h[:8], "big", signed=True)
    return v


class AbstractSqlStore(FilerStore):
    """Works over any DB-API connection; subclasses pick driver+dialect."""

    name = "abstract_sql"
    placeholder = "?"        # sqlite/mysql use ?/%s, postgres uses %s/$n
    upsert_sql: str | None = None  # dialect-specific INSERT..ON CONFLICT

    def __init__(self, conn, **_):
        self._conn = conn
        self._lock = threading.RLock()
        self._create_table()

    def _create_table(self) -> None:
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS filemeta ("
                " dirhash BIGINT,"
                " name VARCHAR(1000),"
                " directory TEXT,"
                " meta TEXT,"
                " PRIMARY KEY (dirhash, name))")
            self._conn.commit()

    def _exec(self, sql: str, args: tuple = ()):
        return self._conn.execute(sql.replace("?", self.placeholder), args)

    # -- contract --

    def insert_entry(self, entry: Entry) -> None:
        d, name = entry.dir_path, entry.name
        if entry.full_path == "/":
            d, name = "/", ""
        meta = json.dumps(entry.to_dict())
        with self._lock:
            sql = self.upsert_sql or (
                "INSERT OR REPLACE INTO filemeta "
                "(dirhash, name, directory, meta) VALUES (?,?,?,?)")
            self._exec(sql, (dir_hash(d), name, d, meta))
            self._conn.commit()

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def _split(self, path: str) -> tuple[str, str]:
        p = path.rstrip("/") or "/"
        if p == "/":
            return "/", ""
        d, _, name = p.rpartition("/")
        return d or "/", name

    def find_entry(self, path: str) -> Entry | None:
        d, name = self._split(path)
        with self._lock:
            row = self._exec(
                "SELECT meta FROM filemeta WHERE dirhash=? AND name=? "
                "AND directory=?", (dir_hash(d), name, d)).fetchone()
        if row is None:
            return None
        return Entry.from_dict(json.loads(row[0]))

    def delete_entry(self, path: str) -> None:
        d, name = self._split(path)
        with self._lock:
            self._exec("DELETE FROM filemeta WHERE dirhash=? AND name=? "
                       "AND directory=?", (dir_hash(d), name, d))
            self._conn.commit()

    def delete_folder_children(self, path: str) -> None:
        p = path.rstrip("/") or "/"
        with self._lock:
            # direct children + entire subtree rows (directory prefix)
            self._exec("DELETE FROM filemeta WHERE dirhash=? AND "
                       "directory=?", (dir_hash(p), p))
            like = ("/%" if p == "/" else p + "/%")
            self._exec("DELETE FROM filemeta WHERE directory LIKE ?",
                       (like,))
            self._conn.commit()

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        d = dir_path.rstrip("/") or "/"
        cmp = ">=" if inclusive else ">"
        with self._lock:
            rows = self._exec(
                f"SELECT meta FROM filemeta WHERE dirhash=? AND "
                f"directory=? AND name {cmp} ? AND name != '' "
                f"ORDER BY name LIMIT ?",
                (dir_hash(d), d, start_file, limit)).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def close(self) -> None:
        with self._lock:
            self._conn.close()


@register_store
class SqliteSqlStore(AbstractSqlStore):
    """sqlite3-backed abstract_sql instance (always available; stands in
    for the mysql/postgres pair in environments without those servers)."""

    name = "sql"

    def __init__(self, path: str = "./filer_sql.db", **_):
        import sqlite3
        super().__init__(sqlite3.connect(path, check_same_thread=False))


class MysqlStore(AbstractSqlStore):
    """Reference: weed/filer2/mysql/mysql_store.go (requires pymysql)."""

    name = "mysql"
    placeholder = "%s"
    upsert_sql = ("INSERT INTO filemeta (dirhash, name, directory, meta) "
                  "VALUES (?,?,?,?) ON DUPLICATE KEY UPDATE meta=VALUES(meta)")

    def __init__(self, host="localhost", port=3306, user="root",
                 password="", database="seaweedfs", **_):
        import pymysql
        conn = pymysql.connect(host=host, port=port, user=user,
                               password=password, database=database,
                               autocommit=False)
        super().__init__(_CursorConn(conn))


class PostgresStore(AbstractSqlStore):
    """Reference: weed/filer2/postgres/postgres_store.go (psycopg2)."""

    name = "postgres"
    placeholder = "%s"
    upsert_sql = ("INSERT INTO filemeta (dirhash, name, directory, meta) "
                  "VALUES (?,?,?,?) ON CONFLICT (dirhash, name) "
                  "DO UPDATE SET meta=EXCLUDED.meta")

    def __init__(self, host="localhost", port=5432, user="postgres",
                 password="", database="seaweedfs", **_):
        import psycopg2
        conn = psycopg2.connect(host=host, port=port, user=user,
                                password=password, dbname=database)
        super().__init__(_CursorConn(conn))


class _CursorConn:
    """Adapt client-server DB-API connections (execute lives on cursors)
    to the sqlite-style conn.execute(...) the shared code uses."""

    def __init__(self, conn):
        self._conn = conn

    def execute(self, sql, args=()):
        cur = self._conn.cursor()
        cur.execute(sql, args)
        return cur

    def commit(self):
        self._conn.commit()

    def close(self):
        self._conn.close()


def _register_if_driver(cls, module: str) -> None:
    try:
        __import__(module)
    except ImportError:
        return
    register_store(cls)


_register_if_driver(MysqlStore, "pymysql")
_register_if_driver(PostgresStore, "psycopg2")
