"""Cassandra filer store (driver-gated).

Reference: weed/filer2/cassandra/cassandra_store.go — table
filemeta(directory, name, meta) partitioned by directory. Registration
is skipped when the cassandra-driver package is absent.
"""

from __future__ import annotations

import json

from cassandra.cluster import Cluster  # gated import

from ..entry import Entry
from ..filerstore import FilerStore, register_store


@register_store
class CassandraStore(FilerStore):
    name = "cassandra"

    def __init__(self, hosts: str = "localhost", keyspace: str = "seaweedfs",
                 **_):
        self._cluster = Cluster(hosts.split(","))
        self._s = self._cluster.connect()
        self._s.execute(
            f"CREATE KEYSPACE IF NOT EXISTS {keyspace} WITH replication="
            "{'class':'SimpleStrategy','replication_factor':1}")
        self._s.set_keyspace(keyspace)
        self._s.execute(
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory text, name text, meta text,"
            " PRIMARY KEY (directory, name))")

    def _split(self, path: str) -> tuple[str, str]:
        p = path.rstrip("/") or "/"
        if p == "/":
            return "/", ""
        d, _, name = p.rpartition("/")
        return d or "/", name

    def insert_entry(self, entry: Entry) -> None:
        d, name = self._split(entry.full_path)
        self._s.execute(
            "INSERT INTO filemeta (directory, name, meta) VALUES (%s,%s,%s)",
            (d, name, json.dumps(entry.to_dict())))

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        d, name = self._split(path)
        rows = self._s.execute(
            "SELECT meta FROM filemeta WHERE directory=%s AND name=%s",
            (d, name))
        row = rows.one()
        if row is None:
            return None
        return Entry.from_dict(json.loads(row.meta))

    def delete_entry(self, path: str) -> None:
        d, name = self._split(path)
        self._s.execute(
            "DELETE FROM filemeta WHERE directory=%s AND name=%s", (d, name))

    def delete_folder_children(self, path: str) -> None:
        p = path.rstrip("/") or "/"
        for e in self.list_directory_entries(p, "", False, 1 << 30):
            if e.is_directory:
                self.delete_folder_children(e.full_path)
            self.delete_entry(e.full_path)

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        d = dir_path.rstrip("/") or "/"
        cmp = ">=" if inclusive else ">"
        rows = self._s.execute(
            f"SELECT meta FROM filemeta WHERE directory=%s AND name {cmp} %s "
            f"LIMIT {int(limit)}", (d, start_file))
        return [Entry.from_dict(json.loads(r.meta)) for r in rows]

    def close(self) -> None:
        self._cluster.shutdown()
