"""Etcd filer store (driver-gated).

Reference: weed/filer2/etcd/etcd_store.go — keys `dir \\x00 name`, range
scans for listings. Registration is skipped when etcd3 is absent.
"""

from __future__ import annotations

import json

import etcd3  # gated: ImportError skips registration (_load_builtin)

from ..entry import Entry
from ..filerstore import FilerStore, register_store

SEP = "\x00"


@register_store
class EtcdStore(FilerStore):
    name = "etcd"

    def __init__(self, servers: str = "localhost:2379", prefix: str = "sw/",
                 **_):
        host, _, port = servers.partition(":")
        self._c = etcd3.client(host=host, port=int(port or 2379))
        self.prefix = prefix

    def _key(self, dir_path: str, name: str) -> str:
        return f"{self.prefix}{dir_path.rstrip('/') or '/'}{SEP}{name}"

    def _split(self, path: str) -> tuple[str, str]:
        p = path.rstrip("/") or "/"
        if p == "/":
            return "/", ""
        d, _, name = p.rpartition("/")
        return d or "/", name

    def insert_entry(self, entry: Entry) -> None:
        d, name = self._split(entry.full_path)
        self._c.put(self._key(d, name), json.dumps(entry.to_dict()))

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        d, name = self._split(path)
        raw, _ = self._c.get(self._key(d, name))
        if raw is None:
            return None
        return Entry.from_dict(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        d, name = self._split(path)
        self._c.delete(self._key(d, name))

    def delete_folder_children(self, path: str) -> None:
        p = path.rstrip("/") or "/"
        # direct children live under `p \x00`; every deeper descendant's
        # key starts with `p /` (its dir path extends p) — both ranges
        # must go or grandchildren are orphaned. For the root, "p/"
        # collapses to "/" (not "//", which matches nothing).
        self._c.delete_prefix(f"{self.prefix}{p}{SEP}")
        self._c.delete_prefix(
            self.prefix + (p if p != "/" else "") + "/")

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        p = dir_path.rstrip("/") or "/"
        out: list[Entry] = []
        for raw, _meta in self._c.get_prefix(f"{self.prefix}{p}{SEP}",
                                             sort_order="ascend"):
            e = Entry.from_dict(json.loads(raw))
            if start_file:
                if e.name < start_file:
                    continue
                if not inclusive and e.name == start_file:
                    continue
            out.append(e)
            if len(out) >= limit:
                break
        return out
