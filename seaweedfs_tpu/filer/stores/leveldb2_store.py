"""8-way sharded embedded store.

Reference: weed/filer2/leveldb2/leveldb2_store.go — 8 leveldb instances,
a directory's children all land in the shard picked by md5(dir)[0] %
dbCount (genKey/genDirectoryKeyPrefix), so listings stay single-shard
while load spreads across DBs.
"""

from __future__ import annotations

import hashlib
import os

from ..entry import Entry
from ..filerstore import FilerStore, register_store
from .leveldb_store import LevelDbStore


@register_store
class LevelDb2Store(FilerStore):
    name = "leveldb2"

    def __init__(self, dir: str = "./filerldb2", db_count: int = 8, **kw):
        self.db_count = db_count
        self.shards = [
            LevelDbStore(dir=os.path.join(dir, f"{i:02d}"), **kw)
            for i in range(db_count)]

    def _shard_of(self, dir_path: str) -> LevelDbStore:
        h = hashlib.md5((dir_path.rstrip("/") or "/").encode()).digest()
        return self.shards[h[0] % self.db_count]

    def _shard_for_path(self, path: str) -> LevelDbStore:
        parent = path.rstrip("/").rsplit("/", 1)[0] or "/"
        return self._shard_of(parent if path != "/" else "/")

    def insert_entry(self, entry: Entry) -> None:
        self._shard_of(entry.dir_path if entry.full_path != "/"
                       else "/").insert_entry(entry)

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        return self._shard_for_path(path).find_entry(path)

    def delete_entry(self, path: str) -> None:
        self._shard_for_path(path).delete_entry(path)

    def delete_folder_children(self, path: str) -> None:
        # children live in shard(path); recurse so grandchildren (in
        # other shards) go too
        children = self._shard_of(path).list_directory_entries(
            path, "", False, 1 << 30)
        for child in children:
            if child.is_directory:
                self.delete_folder_children(child.full_path)
            self.delete_entry(child.full_path)

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        return self._shard_of(dir_path).list_directory_entries(
            dir_path, start_file, inclusive, limit)

    def close(self) -> None:
        for s in self.shards:
            s.close()
