"""Embedded durable KV filer store (leveldb-class).

Reference: weed/filer2/leveldb/leveldb_store.go — entries keyed by
`dir \\x00 name`, prefix scans for listings. No goleveldb binding exists
here, so this is a small log-structured store of its own: a JSONL
write-ahead log replayed into the in-memory sorted index on open, with
snapshot compaction once the log accumulates enough dead records. Same
durability class (fsync'd WAL), same contract.
"""

from __future__ import annotations

import json
import os
import threading

from ..entry import Entry
from ..filerstore import FilerStore, register_store
from .memory_store import MemoryStore


@register_store
class LevelDbStore(FilerStore):
    name = "leveldb"

    def __init__(self, dir: str = "./filerldb", sync: bool = False,
                 compact_threshold: int = 50_000, **_):
        os.makedirs(dir, exist_ok=True)
        self.dir = dir
        self.sync = sync
        self.compact_threshold = compact_threshold
        self._lock = threading.RLock()
        self._mem = MemoryStore()
        self._ops_since_compact = 0
        self._log_path = os.path.join(dir, "wal.jsonl")
        self._snap_path = os.path.join(dir, "snapshot.jsonl")
        self._replay()
        self._log = open(self._log_path, "a")

    # -- persistence --

    def _replay(self) -> None:
        for path in (self._snap_path, self._log_path):
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for raw in f:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        rec = json.loads(raw)
                    except json.JSONDecodeError:
                        continue  # torn tail write after a crash
                    if rec["op"] == "put":
                        self._mem.insert_entry(Entry.from_dict(rec["e"]))
                    elif rec["op"] == "del":
                        self._mem.delete_entry(rec["path"])
                    elif rec["op"] == "delchildren":
                        self._mem.delete_folder_children(rec["path"])

    def _append(self, rec: dict) -> None:
        self._log.write(json.dumps(rec) + "\n")
        self._log.flush()
        if self.sync:
            os.fsync(self._log.fileno())
        self._ops_since_compact += 1
        if self._ops_since_compact >= self.compact_threshold:
            self._compact()

    def _compact(self) -> None:
        """Rewrite state as a snapshot, truncate the WAL."""
        tmp = self._snap_path + ".tmp"
        with open(tmp, "w") as f:
            for entry in self._mem._entries.values():
                f.write(json.dumps(
                    {"op": "put", "e": entry.to_dict()}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        self._log.close()
        self._log = open(self._log_path, "w")
        self._ops_since_compact = 0

    # -- FilerStore contract --

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._mem.insert_entry(entry)
            self._append({"op": "put", "e": entry.to_dict()})

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        with self._lock:
            return self._mem.find_entry(path)

    def delete_entry(self, path: str) -> None:
        with self._lock:
            self._mem.delete_entry(path)
            self._append({"op": "del", "path": path})

    def delete_folder_children(self, path: str) -> None:
        with self._lock:
            self._mem.delete_folder_children(path)
            self._append({"op": "delchildren", "path": path})

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        with self._lock:
            return self._mem.list_directory_entries(
                dir_path, start_file, inclusive, limit)

    def close(self) -> None:
        with self._lock:
            self._compact()
            self._log.close()
