"""In-memory filer store (test/default store; the reference's baseline is
leveldb — filer2/leveldb/leveldb_store.go)."""

from __future__ import annotations

import bisect
import threading

from ..entry import Entry
from ..filerstore import FilerStore, register_store


@register_store
class MemoryStore(FilerStore):
    name = "memory"

    def __init__(self, **_):
        self._lock = threading.RLock()
        # dir_path -> sorted list of child names; full_path -> Entry
        self._dirs: dict[str, list[str]] = {}
        self._entries: dict[str, Entry] = {}

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            path = entry.full_path
            self._entries[path] = entry
            if path != "/":
                names = self._dirs.setdefault(entry.dir_path, [])
                i = bisect.bisect_left(names, entry.name)
                if i >= len(names) or names[i] != entry.name:
                    names.insert(i, entry.name)

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        with self._lock:
            return self._entries.get(path)

    def delete_entry(self, path: str) -> None:
        with self._lock:
            e = self._entries.pop(path, None)
            if e is not None and path != "/":
                names = self._dirs.get(e.dir_path, [])
                i = bisect.bisect_left(names, e.name)
                if i < len(names) and names[i] == e.name:
                    names.pop(i)

    def count_entries(self) -> int:
        with self._lock:
            # root stub excluded: it exists on every shard
            return sum(1 for p in self._entries if p != "/")

    def delete_folder_children(self, path: str) -> None:
        with self._lock:
            prefix = path.rstrip("/") or "/"
            doomed = [d for d in self._dirs
                      if d == prefix or d.startswith(
                          (prefix if prefix != "/" else "") + "/")]
            for d in doomed:
                for name in self._dirs.pop(d, []):
                    child = ("" if d == "/" else d) + "/" + name
                    self._entries.pop(child, None)

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        with self._lock:
            prefix = dir_path.rstrip("/") or ""
            names = self._dirs.get(prefix or "/", [])
            i = bisect.bisect_left(names, start_file) if start_file else 0
            if start_file and not inclusive and i < len(names) \
                    and names[i] == start_file:
                i += 1
            out = []
            for name in names[i:i + limit]:
                e = self._entries.get(f"{prefix}/{name}")
                if e is not None:
                    out.append(e)
            return out
