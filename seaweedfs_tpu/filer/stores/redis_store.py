"""Redis filer store (driver-gated).

Reference: weed/filer2/redis/universal_redis_store.go — entry JSON at
key=full path, directory listings as a sorted set `<dir>\\x00children`
(here a zset named `dir:<path>`); import fails cleanly when the redis
client library is absent.
"""

from __future__ import annotations

import json

import redis  # gated: ImportError skips registration (_load_builtin)

from ..entry import Entry
from ..filerstore import FilerStore, register_store


@register_store
class RedisStore(FilerStore):
    name = "redis"

    DIR_LIST_KEY = "dir:{}"

    def __init__(self, host: str = "localhost", port: int = 6379,
                 password: str = "", database: int = 0, **_):
        self._r = redis.Redis(host=host, port=port, password=password,
                              db=database)

    def insert_entry(self, entry: Entry) -> None:
        self._r.set(entry.full_path, json.dumps(entry.to_dict()))
        if entry.full_path != "/":
            self._r.zadd(self.DIR_LIST_KEY.format(entry.dir_path),
                         {entry.name: 0})

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        raw = self._r.get(path)
        if raw is None:
            return None
        return Entry.from_dict(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        self._r.delete(path)
        if path != "/":
            d, _, name = (path.rstrip("/")).rpartition("/")
            self._r.zrem(self.DIR_LIST_KEY.format(d or "/"), name)

    def delete_folder_children(self, path: str) -> None:
        p = path.rstrip("/") or "/"
        key = self.DIR_LIST_KEY.format(p)
        for name in self._r.zrange(key, 0, -1):
            child = f"{p.rstrip('/')}/{name.decode()}"
            e = self.find_entry(child)
            if e is not None and e.is_directory:
                self.delete_folder_children(child)
            self._r.delete(child)
        self._r.delete(key)

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        p = dir_path.rstrip("/") or "/"
        lo = f"[{start_file}" if start_file else "-"
        names = self._r.zrangebylex(self.DIR_LIST_KEY.format(p), lo, "+")
        out: list[Entry] = []
        for raw in names:
            name = raw.decode()
            if not inclusive and name == start_file:
                continue
            e = self.find_entry(f"{p.rstrip('/')}/{name}")
            if e is not None:
                out.append(e)
            if len(out) >= limit:
                break
        return out

    def close(self) -> None:
        self._r.close()
