"""SQLite filer store — the embedded durable store.

Plays the role of the reference's leveldb2 default (8-way sharded leveldb,
filer2/leveldb2/leveldb2_store.go) and shares its schema idea with
abstract_sql (filer2/abstract_sql/abstract_sql_store.go): rows keyed by
(directory, name) with a serialized meta blob, so directory listings are an
indexed range scan.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading

from ..entry import Entry
from ..filerstore import FilerStore, register_store


@register_store
class SqliteStore(FilerStore):
    name = "sqlite"

    def __init__(self, path: str = "filer.db", **_):
        self.path = path
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        self._local = threading.local()
        with self._conn() as c:
            c.execute("""CREATE TABLE IF NOT EXISTS filemeta (
                directory TEXT NOT NULL,
                name TEXT NOT NULL,
                meta TEXT NOT NULL,
                PRIMARY KEY (directory, name))""")

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            conn.isolation_level = None  # autocommit
            self._local.conn = conn
        return conn

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        p = path.rstrip("/") or "/"
        if p == "/":
            return "", "/"
        d, _, n = p.rpartition("/")
        return d or "/", n

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        self._conn().execute(
            "INSERT OR REPLACE INTO filemeta (directory,name,meta) "
            "VALUES (?,?,?)", (d, n, json.dumps(entry.to_dict())))

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        d, n = self._split(path)
        row = self._conn().execute(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (d, n)).fetchone()
        if row is None:
            return None
        return Entry.from_dict(json.loads(row[0]))

    def delete_entry(self, path: str) -> None:
        d, n = self._split(path)
        self._conn().execute(
            "DELETE FROM filemeta WHERE directory=? AND name=?", (d, n))

    def count_entries(self) -> int:
        row = self._conn().execute(
            "SELECT COUNT(*) FROM filemeta WHERE name != '/'").fetchone()
        return int(row[0])

    def delete_folder_children(self, path: str) -> None:
        p = path.rstrip("/") or "/"
        esc = p.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")
        like = "/%" if p == "/" else esc + "/%"
        self._conn().execute(
            "DELETE FROM filemeta WHERE directory=? OR directory LIKE ? "
            "ESCAPE '\\'", (p, like))

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        d = dir_path.rstrip("/") or "/"
        op = ">=" if inclusive else ">"
        rows = self._conn().execute(
            f"SELECT meta FROM filemeta WHERE directory=? AND name {op} ? "
            f"ORDER BY name LIMIT ?", (d, start_file, limit)).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
