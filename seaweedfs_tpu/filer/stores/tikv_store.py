"""TiKV filer store (driver-gated).

Reference: weed/filer2/tikv/tikv_store.go — raw KV keys
`dir \\x00 name`, Scan for listings, DeleteRange for subtree removal.
Registration is skipped when the tikv_client package is absent.
"""

from __future__ import annotations

import json

import tikv_client  # gated: ImportError skips registration (_load_builtin)

from ..entry import Entry
from ..filerstore import FilerStore, register_store

SEP = "\x00"


@register_store
class TikvStore(FilerStore):
    name = "tikv"

    def __init__(self, pdaddrs: str = "localhost:2379", client=None, **_):
        self._c = client if client is not None else \
            tikv_client.RawClient.connect(pdaddrs)

    def _key(self, dir_path: str, name: str) -> bytes:
        return f"{dir_path.rstrip('/') or '/'}{SEP}{name}".encode()

    def _split(self, path: str) -> tuple[str, str]:
        p = path.rstrip("/") or "/"
        if p == "/":
            return "/", ""
        d, _, name = p.rpartition("/")
        return d or "/", name

    def insert_entry(self, entry: Entry) -> None:
        d, name = self._split(entry.full_path)
        self._c.put(self._key(d, name),
                    json.dumps(entry.to_dict()).encode())

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        d, name = self._split(path)
        raw = self._c.get(self._key(d, name))
        if raw is None:
            return None
        return Entry.from_dict(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        d, name = self._split(path)
        self._c.delete(self._key(d, name))

    def delete_folder_children(self, path: str) -> None:
        # recurse into subdirectories first (their children live under
        # different key prefixes), then DeleteRange this directory's span
        for e in self.list_directory_entries(path, "", False, 1 << 30):
            if e.is_directory:
                self.delete_folder_children(e.full_path)
        p = path.rstrip("/") or "/"
        # end key must be raw bytes: "\xff".encode() UTF-8s to C3 BF,
        # excluding names whose bytes sort above it
        self._c.delete_range(f"{p}{SEP}".encode(),
                             f"{p}{SEP}".encode() + b"\xff")

    def list_directory_entries(self, dir_path: str, start_file: str,
                               inclusive: bool, limit: int) -> list[Entry]:
        p = dir_path.rstrip("/") or "/"
        start = f"{p}{SEP}{start_file}".encode()
        end = f"{p}{SEP}".encode() + b"\xff"
        out: list[Entry] = []
        for key, raw in self._c.scan(start, end, limit + 1):
            name = key.decode().split(SEP, 1)[1]
            if start_file and not inclusive and name == start_file:
                continue
            out.append(Entry.from_dict(json.loads(raw)))
            if len(out) >= limit:
                break
        return out
