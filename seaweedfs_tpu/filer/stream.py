"""Streaming read assembly over a chunk list.

Reference: weed/filer2/stream.go:12-47 (StreamContent). Yields the bytes
of [offset, offset+length) in order, zero-filling sparse holes between
visible intervals and any short tail so the byte count always matches the
declared length.

When the client carries a chunk cache (util/chunk_cache), each view
whose chunk fits the cache is served as a slice of the WHOLE cached
chunk (weed/filer/reader_at.go rides its chunk cache the same way): a
hot object's re-read never touches a volume server, and concurrent
cold readers of one chunk collapse into a single fetch through the
client's singleflight.
"""

from __future__ import annotations

from .filechunks import FileChunk, view_from_chunks

_ZERO_BLOCK = 64 * 1024


async def stream_chunk_views(client, chunks: list[FileChunk], offset: int,
                             length: int):
    """Async-generate data blocks for [offset, offset+length).

    Each view streams through `client.read_stream`, which carries the
    degraded-read failover: a replica dying mid-chunk rotates to the
    next location and resumes via Range, so the filer response keeps
    flowing instead of aborting. Only a full miss (every holder down)
    propagates to the caller (typically translated into a transport
    abort once headers are sent).
    """
    cc = getattr(client, "chunk_cache", None)
    sizes = {c.file_id: c.size for c in chunks} if cc is not None else {}
    pos = offset
    stop = offset + length
    for view in view_from_chunks(chunks, offset, length):
        while pos < view.logic_offset:  # hole: reads as zeros
            n = min(_ZERO_BLOCK, view.logic_offset - pos)
            yield b"\x00" * n
            pos += n
        whole = sizes.get(view.file_id, 0)
        if cc is not None and 0 < whole <= cc.max_item_size \
                and (2 * view.size >= whole
                     or cc.contains(view.file_id)):
            # whole-chunk path: cache + singleflight. Taken when the
            # chunk is already resident (a range of a hot chunk is a
            # free slice) or the view covers at least half of it —
            # a cold small range sticks to the ranged network stream
            # below instead of paying up-to-max_item_size
            # amplification to warm a chunk it may never revisit.
            # A short chunk yields fewer bytes and the hole/tail
            # zero-fill keeps the byte count exact, as before.
            data = await client.chunk_bytes(view.file_id, whole)
            block = data[view.offset:view.offset + view.size]
            if block:
                yield block
                pos += len(block)
            continue
        async for data in client.read_stream(view.file_id, view.offset,
                                             view.size):
            yield data
            pos += len(data)
    while pos < stop:  # tail hole / short chunk
        n = min(_ZERO_BLOCK, stop - pos)
        yield b"\x00" * n
        pos += n
