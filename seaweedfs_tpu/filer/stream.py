"""Streaming read assembly over a chunk list.

Reference: weed/filer2/stream.go:12-47 (StreamContent). Yields the bytes
of [offset, offset+length) in order, zero-filling sparse holes between
visible intervals and any short tail so the byte count always matches the
declared length.
"""

from __future__ import annotations

from .filechunks import FileChunk, view_from_chunks

_ZERO_BLOCK = 64 * 1024


async def stream_chunk_views(client, chunks: list[FileChunk], offset: int,
                             length: int):
    """Async-generate data blocks for [offset, offset+length).

    Each view streams through `client.read_stream`, which carries the
    degraded-read failover: a replica dying mid-chunk rotates to the
    next location and resumes via Range, so the filer response keeps
    flowing instead of aborting. Only a full miss (every holder down)
    propagates to the caller (typically translated into a transport
    abort once headers are sent).
    """
    pos = offset
    stop = offset + length
    for view in view_from_chunks(chunks, offset, length):
        while pos < view.logic_offset:  # hole: reads as zeros
            n = min(_ZERO_BLOCK, view.logic_offset - pos)
            yield b"\x00" * n
            pos += n
        async for data in client.read_stream(view.file_id, view.offset,
                                             view.size):
            yield data
            pos += len(data)
    while pos < stop:  # tail hole / short chunk
        n = min(_ZERO_BLOCK, stop - pos)
        yield b"\x00" * n
        pos += n
