from .resizing import resized  # noqa: F401
from .orientation import fix_jpeg_orientation  # noqa: F401
