"""EXIF orientation normalization on JPEG upload.

Reference: weed/images/orientation.go `FixJpgOrientation` — applied in
needle upload parsing (weed/storage/needle/needle.go ParseUpload) so
stored bytes render upright everywhere.
"""

from __future__ import annotations

import io


def fix_jpeg_orientation(data: bytes) -> bytes:
    """Bake the EXIF orientation into the pixel data of a JPEG.

    Returns the input unchanged when it is not a JPEG, carries no
    orientation tag (or orientation 1), or cannot be decoded.
    """
    if len(data) < 4 or data[:2] != b"\xff\xd8":
        return data
    try:
        from PIL import Image, ImageOps
    except ImportError:  # pragma: no cover
        return data
    try:
        img = Image.open(io.BytesIO(data))
        exif = img.getexif()
        orientation = exif.get(0x0112, 1)
        if orientation == 1:
            return data
        fixed = ImageOps.exif_transpose(img)
        out = io.BytesIO()
        fixed.save(out, format="JPEG", quality=95)
        return out.getvalue()
    except Exception:
        return data
