"""On-read image resizing.

Reference: weed/images/resizing.go:15-50 — `Resized(ext, data, width,
height, mode)` resizes png/jpg/gif on GET when `?width=&height=&mode=`
query params are present (hooked at
weed/server/volume_server_handlers_read.go:211-227). Modes (matching resizing.go's imaging calls):
  - ""     : when both dims given, stretch to exactly (w, h); with one
             dim, proportional scale to that dimension.
  - "fit"  : proportional fit within the (w, h) box.
  - "fill" : scale + center-crop so the image exactly fills (w, h).
"""

from __future__ import annotations

import io

_FORMATS = {
    "image/png": "PNG",
    "image/jpeg": "JPEG",
    "image/jpg": "JPEG",
    "image/gif": "GIF",
    "image/webp": "WEBP",
}


def resizable(mime: str) -> bool:
    return mime.lower() in _FORMATS


def resized(mime: str, data: bytes, width: int, height: int,
            mode: str = "") -> bytes:
    """Return the resized image bytes (same encoding as the input).

    Returns `data` unchanged when the mime type is not an image, the
    requested box is degenerate, or the image is already small enough.
    """
    fmt = _FORMATS.get(mime.lower())
    if fmt is None or (width <= 0 and height <= 0):
        return data
    try:
        from PIL import Image, ImageOps
    except ImportError:  # pragma: no cover - PIL is baked into the image
        return data
    try:
        img = Image.open(io.BytesIO(data))
        img.load()
    except Exception:
        return data
    ow, oh = img.size
    if width > 0 and height > 0:
        if ow <= width and oh <= height:
            return data
        if mode == "fill":
            img = ImageOps.fit(img, (width, height))
        elif mode == "fit":
            img.thumbnail((width, height))
        else:  # "": stretch to the exact box (imaging.Resize)
            img = img.resize((width, height))
    else:
        # single-dimension proportional scale
        if width > 0:
            if ow <= width:
                return data
            img = img.resize((width, max(1, round(oh * width / ow))))
        else:
            if oh <= height:
                return data
            img = img.resize((max(1, round(ow * height / oh)), height))
    out = io.BytesIO()
    if fmt == "JPEG" and img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    img.save(out, format=fmt)
    return out.getvalue()
