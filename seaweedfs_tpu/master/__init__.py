"""master subpackage."""
