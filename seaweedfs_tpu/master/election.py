"""Leader election among masters: raft-lite over HTTP.

Reference: weed/server/raft_server.go:28-97 wraps chrislusf/raft, but the
usage is shallow — peer membership plus ONE replicated value, MaxVolumeId
(topology/cluster_commands.go:9-29), with leader identity surfaced to
volume servers in heartbeat responses (master_grpc_server.go:165-175) and
non-leader HTTP proxied to the leader (master_server.go:153-185).

This module re-expresses exactly that contract as term-based election
(RequestVote / AppendEntries-style leader pulses) without a general
replicated log: the single replicated value rides on the leader pulse.
"""

from __future__ import annotations

from ..security import tls

import asyncio
import json
import os
import random
import time

import aiohttp

from ..util import glog


class Election:
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"

    @staticmethod
    def _norm(url: str) -> str:
        host, _, port = url.strip().partition(":")
        if host in ("localhost", ""):
            host = "127.0.0.1"
        return f"{host}:{port}"

    def __init__(self, me: str, peers: list[str],
                 election_timeout: tuple[float, float] = (1.0, 2.0),
                 pulse: float = 0.3,
                 state_path: str | None = None):
        self.me = self._norm(me)
        # peers excludes self (normalized, so localhost == 127.0.0.1);
        # empty peers == single-master mode
        self.peers = [p for p in map(self._norm, peers) if p != self.me]
        self.single = not self.peers
        self.majority = (len(self.peers) + 1) // 2 + 1
        self.timeout_range = election_timeout
        self.pulse = pulse
        self.term = 0
        self.voted_for: str | None = None
        # durable (term, votedFor), written BEFORE any vote takes effect:
        # without it a restarted master forgets it voted and can grant a
        # second vote in the same term — a split-brain window the
        # reference's raft layer persists away (raft_server.go:60-76)
        self.state_path = state_path
        if state_path and os.path.exists(state_path):
            try:
                with open(state_path) as f:
                    st = json.load(f)
                self.term = int(st.get("term", 0))
                self.voted_for = st.get("voted_for") or None
            except (OSError, ValueError) as e:
                raise SystemExit(
                    f"election state {state_path} unreadable/corrupt: {e};"
                    f" repair or remove it explicitly") from e
        self.role = self.LEADER if self.single else self.FOLLOWER
        self.leader: str | None = self.me if self.single else None
        self.last_pulse = time.monotonic()
        # last time a leader pulse round reached a quorum (leader lease)
        self._last_quorum = time.monotonic()
        # replicated value (MaxVolumeId) exchange hooks, set by MasterServer
        self.get_max_volume_id = lambda: 0
        self.adopt_max_volume_id = lambda v: None
        self._http: aiohttp.ClientSession | None = None
        self._task: asyncio.Task | None = None

    @property
    def is_leader(self) -> bool:
        return self.role == self.LEADER

    def _persist(self) -> None:
        """Atomically checkpoint (term, votedFor). Must complete before
        the vote/term change is acted on (raft durability rule)."""
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self.single:
            return
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=max(self.pulse * 2, 0.5)))
        self.last_pulse = time.monotonic()
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._http:
            await self._http.close()

    # ---- incoming RPCs (wired as HTTP handlers by MasterServer) ----

    def on_vote_request(self, term: int, candidate: str,
                        max_volume_id: int = 0) -> dict:
        if self.single:
            # a single-mode master is not part of any quorum; never let a
            # misconfigured peer demote it (it has no loop to recover)
            return {"term": self.term, "granted": False}
        if candidate == self.me:
            # our own vote request routed back to us through a peer-list
            # entry that is really our address: only the local self-vote
            # in _campaign counts
            return {"term": self.term, "granted": False}
        bumped = term > self.term
        if bumped:
            self.term = term
            self.voted_for = None
            self._step_down()
        # up-to-date check on the one replicated value: never elect a
        # candidate that would reissue already-used volume ids (the
        # raft log-freshness vote rule collapsed to MaxVolumeId)
        granted = (term == self.term
                   and self.voted_for in (None, candidate)
                   and max_volume_id >= self.get_max_volume_id())
        if granted:
            self.voted_for = candidate
            self.last_pulse = time.monotonic()
        if granted or bumped:
            self._persist()  # durable before the reply leaves this node
        return {"term": self.term, "granted": granted}

    def on_leader_pulse(self, term: int, leader: str,
                        max_volume_id: int) -> dict:
        if self.single:
            return {"term": self.term, "ok": False}
        if term >= self.term:
            if term > self.term:
                self.voted_for = None
                self.term = term
                self._persist()
            self.leader = leader
            if leader != self.me:
                self._step_down()
            self.last_pulse = time.monotonic()
            self.adopt_max_volume_id(max_volume_id)
            return {"term": self.term, "ok": True}
        return {"term": self.term, "ok": False}

    def _step_down(self) -> None:
        if self.role != self.FOLLOWER:
            glog.info("%s: stepping down from %s at term %d",
                      self.me, self.role, self.term)
            self.role = self.FOLLOWER

    # ---- the election / heartbeat loop ----

    async def _loop(self) -> None:
        while True:
            if self.role == self.LEADER:
                await self._broadcast_pulse()
                # leader lease: a leader partitioned from every peer must
                # stop serving writes before the others elect a successor,
                # or two masters assign volume ids concurrently
                if time.monotonic() - self._last_quorum \
                        > self.timeout_range[0] * 0.8:
                    self._step_down()
                    self.leader = None
                    self.last_pulse = time.monotonic()
                await asyncio.sleep(self.pulse)
            else:
                timeout = random.uniform(*self.timeout_range)
                await asyncio.sleep(self.pulse / 2)
                if time.monotonic() - self.last_pulse > timeout:
                    await self._campaign()

    async def _campaign(self) -> None:
        self.role = self.CANDIDATE
        self.term += 1
        term = self.term
        self.voted_for = self.me
        self._persist()  # self-vote must be durable before soliciting
        self.leader = None
        votes = 1  # self-vote

        async def ask(peer: str) -> bool:
            try:
                async with self._http.post(
                        tls.url(peer, "/raft/vote"),
                        json={"term": term, "candidate": self.me,
                              "max_volume_id": self.get_max_volume_id()},
                ) as resp:
                    body = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return False
            if body.get("term", 0) > self.term:
                self.term = body["term"]
                self.voted_for = None
                self._persist()
                self._step_down()
            return bool(body.get("granted"))

        results = await asyncio.gather(*(ask(p) for p in self.peers))
        votes += sum(results)
        if self.role == self.CANDIDATE and self.term == term \
                and votes >= self.majority:
            glog.info("%s: elected leader at term %d (%d/%d votes)",
                      self.me, term, votes, len(self.peers) + 1)
            self.role = self.LEADER
            self.leader = self.me
            self._last_quorum = time.monotonic()
            await self._broadcast_pulse()
        else:
            self._step_down()
            # reset the election timer: retrying immediately would keep
            # split candidates colliding in lockstep (the randomized
            # timeout only de-syncs them if both wait a fresh one)
            self.last_pulse = time.monotonic()

    async def _broadcast_pulse(self) -> int:
        """One leader pulse round. Returns the ack count (incl. self) and
        refreshes the leader lease when it reaches a quorum."""
        body = {"term": self.term, "leader": self.me,
                "max_volume_id": self.get_max_volume_id()}

        async def send(peer: str) -> bool:
            try:
                async with self._http.post(
                        tls.url(peer, "/raft/heartbeat"), json=body) as resp:
                    reply = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return False
            if reply.get("term", 0) > self.term:
                self.term = reply["term"]
                self.voted_for = None
                self._persist()
                self._step_down()
                return False
            return bool(reply.get("ok"))

        results = await asyncio.gather(*(send(p) for p in self.peers))
        acks = 1 + sum(results)
        if acks >= self.majority:
            self._last_quorum = time.monotonic()
        return acks

    async def commit_max_volume_id(self) -> bool:
        """Synchronously replicate the current MaxVolumeId to a quorum.

        The reference raft-commits MaxVolumeIdCommand before using a grown
        volume id (cluster_commands.go:23); a value not acked by a
        majority may be lost on leader crash and reissued."""
        if self.single:
            return True
        if not self.is_leader:
            return False
        acks = await self._broadcast_pulse()
        return acks >= self.majority
