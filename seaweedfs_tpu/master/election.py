"""Master consensus: a compact raft with a replicated log + snapshots.

Reference: weed/server/raft_server.go:28-97 runs chrislusf/raft with
log + snapshotting; the only command the reference ever replicates is
MaxVolumeIdCommand (topology/cluster_commands.go:9-29), with leader
identity surfaced to volume servers in heartbeat responses
(master_grpc_server.go:165-175) and non-leader HTTP proxied to the
leader (master_server.go:153-185).

This module implements the same machinery natively: term-based election
with log-freshness vote checks, an AppendEntries log with conflict
truncation and per-peer next/match tracking, quorum commit, a leader
lease (a partitioned leader steps down before the majority elects a
successor — the split-brain window the round-4 verdict flagged), and
log-compaction snapshots with InstallSnapshot for lagging peers. The
state machine is the reference's: the MaxVolumeId watermark.

Wire surface (HTTP, mTLS-scoped like the reference's raft transport):
  POST /raft/vote       {term, candidate, last_log_index, last_log_term}
  POST /raft/heartbeat  {term, leader, prev_index, prev_term,
                         entries: [{term, cmd}], commit}
  POST /raft/snapshot   {term, leader, last_index, last_term, value}
"""

from __future__ import annotations

from ..security import tls

import asyncio
import json
import os
import random
import time

import aiohttp

from ..util import events, failpoints, glog, tracing
from ..util.frame import FrameChannelError, FrameHub

# compact the log once it outgrows this many entries (each entry is one
# volume-id bump; the reference's raft snapshots on a size threshold too)
SNAPSHOT_THRESHOLD = 64


class Election:
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"

    @staticmethod
    def _norm(url: str) -> str:
        host, _, port = url.strip().partition(":")
        if host in ("localhost", ""):
            host = "127.0.0.1"
        return f"{host}:{port}"

    def __init__(self, me: str, peers: list[str],
                 election_timeout: tuple[float, float] = (1.0, 2.0),
                 pulse: float = 0.3,
                 state_path: str | None = None,
                 jwt_key: str = ""):
        self.me = self._norm(me)
        # peers excludes self (normalized, so localhost == 127.0.0.1);
        # empty peers == single-master mode
        self.peers = [p for p in map(self._norm, peers) if p != self.me]
        self.single = not self.peers
        self.majority = (len(self.peers) + 1) // 2 + 1
        self.timeout_range = election_timeout
        self.pulse = pulse
        # per-attempt RPC deadline, strictly shorter than the minimum
        # election timeout: one hung peer socket must never stretch a
        # vote fan-out (or a replication round) past the next timeout
        # fire — the campaign would then collide with its own retry
        # forever instead of re-randomizing
        self.attempt_timeout = max(0.05, min(election_timeout[0] * 0.5,
                                             pulse * 2))
        self.term = 0
        self.voted_for: str | None = None
        # replicated log: absolute index = snap.last_index + 1 + pos.
        # `value` is the applied MaxVolumeId watermark; `seq` the applied
        # file-id reservation ceiling (ids below it are spoken for by
        # some committed reservation window — sequence.RaftSequencer)
        self.snap = {"last_index": 0, "last_term": 0, "value": 0,
                     "seq": 0, "shard_epoch": 0, "shard_map": None}
        self.entries: list[dict] = []
        self.commit = 0
        self.applied = 0
        self.applied_value = 0
        self.applied_seq = 0
        # applied filer shard map (filer/shard.py): epoch + the last
        # committed map dict; transitions CAS on the epoch at APPLY
        # time so a deposed leader's stale map proposal is a no-op
        self.applied_shard_epoch = 0
        self.applied_shard: dict | None = None
        # durable (term, votedFor, snapshot, log), written BEFORE any
        # vote/append takes effect: without it a restarted master forgets
        # it voted and can grant a second vote in the same term — a
        # split-brain window the reference's raft layer persists away
        # (raft_server.go:60-76)
        self.state_path = state_path
        if state_path and os.path.exists(state_path):
            try:
                with open(state_path) as f:
                    st = json.load(f)
                self.term = int(st.get("term", 0))
                self.voted_for = st.get("voted_for") or None
                self.snap = st.get("snapshot", self.snap)
                self.entries = st.get("entries", [])
            except (OSError, ValueError) as e:
                raise SystemExit(
                    f"election state {state_path} unreadable/corrupt: {e};"
                    f" repair or remove it explicitly") from e
            self.snap.setdefault("seq", 0)   # pre-HA state files
            self.snap.setdefault("shard_epoch", 0)   # pre-shard files
            self.snap.setdefault("shard_map", None)
            self.commit = self.applied = self.snap["last_index"]
            self.applied_value = self.snap["value"]
            self.applied_seq = self.snap["seq"]
            self.applied_shard_epoch = self.snap["shard_epoch"]
            self.applied_shard = self.snap["shard_map"]
        self.role = self.LEADER if self.single else self.FOLLOWER
        self.leader: str | None = self.me if self.single else None
        self.last_pulse = time.monotonic()
        # last time a leader round reached a quorum (leader lease)
        self._last_quorum = time.monotonic()
        # leader-side replication cursors (valid while role == LEADER)
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        # replicated value (MaxVolumeId) exchange hooks, set by MasterServer
        self.get_max_volume_id = lambda: 0
        self.adopt_max_volume_id = lambda v: None
        # replicated fid-reservation hook (sequence.RaftSequencer):
        # called at APPLY time for every committed seq_reserve window,
        # in log order, with the entry's author and term so only the
        # reserving leader claims the window it committed
        self.adopt_seq_window = lambda start, end, by, term: None
        # replicated filer shard map hook (MasterServer mirrors the
        # applied map for /cluster/shards), called at APPLY time
        self.adopt_shard_map = lambda epoch, shard_map: None
        self._http: aiohttp.ClientSession | None = None
        # frame fabric: one persistent multiplexed channel per raft
        # peer (HELLO identity signed with the cluster jwt key when
        # set), with per-attempt channel deadlines; any frame failure
        # falls back to the aiohttp POST below
        self.jwt_key = jwt_key
        self.frame_hub: FrameHub | None = None
        self._task: asyncio.Task | None = None
        # deferred-durability machinery: sync mutators mark, async
        # call sites flush before the state is acted on
        self._dirty = False
        self._flush_lock = asyncio.Lock()
        # one replicated command in flight at a time: two interleaved
        # append_command drivers would race next_index bookkeeping
        self._append_lock = asyncio.Lock()
        # last leader identity this node journaled (change detection)
        self._noted_leader: str | None = None
        if self.single:
            # leader by fiat: journal + gauges so a single-mode master
            # is observable through the same surfaces as a quorum one
            self._note_leader(self.me)
        self._update_gauges()

    # ---- observability (journal + gauges) ----

    def _note_leader(self, leader: str | None) -> None:
        """Journal a leadership change exactly once per transition —
        every node records the change it OBSERVED (wall_ms deltas
        across the fleet bound the failover window)."""
        if leader == self._noted_leader or not leader:
            return
        self._noted_leader = leader
        events.record("raft_leader_change", leader=leader,
                      term=self.term, me=self.me,
                      role=self.role, single=self.single)

    def _update_gauges(self) -> None:
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        metrics.RAFT_TERM.set(self.term)
        metrics.RAFT_COMMIT_INDEX.set(self.commit)
        metrics.RAFT_IS_LEADER.set(1 if self.is_leader else 0)

    @property
    def is_leader(self) -> bool:
        return self.role == self.LEADER

    # ---- log primitives ----

    def last_index(self) -> int:
        return self.snap["last_index"] + len(self.entries)

    def last_log_term(self) -> int:
        return (self.entries[-1]["term"] if self.entries
                else self.snap["last_term"])

    def _term_at(self, idx: int) -> int | None:
        if idx == self.snap["last_index"]:
            return self.snap["last_term"]
        pos = idx - self.snap["last_index"] - 1
        if 0 <= pos < len(self.entries):
            return self.entries[pos]["term"]
        return None

    def _mark_dirty(self) -> None:
        """Record that (term, votedFor, snapshot, log) changed. The
        change becomes durable at the next ``flush()`` — and every RPC
        reply / vote solicitation / replication round flushes BEFORE
        acting on the state (raft durability rule), so the guarantee
        is unchanged from the old write-inline ``_persist``; only the
        fsync moved off the event loop."""
        self._dirty = True

    def _state_payload(self) -> str:
        return json.dumps({"term": self.term,
                           "voted_for": self.voted_for,
                           "snapshot": self.snap,
                           "entries": self.entries})

    def _write_state(self, payload: str) -> None:
        """Atomic checkpoint write (tmp + fsync + rename); runs on the
        executor so a slow disk never stalls the loop serving every
        master request."""
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    async def flush(self) -> None:
        """Make every marked state change durable. Serialization
        happens on the loop under the flush lock (so the snapshot is
        internally consistent), the write+fsync on the executor. A
        failed write re-marks dirty and re-raises — the caller's RPC
        reply must not leave the node claiming durability it lacks."""
        if not self.state_path or not self._dirty:
            return
        async with self._flush_lock:
            if not self._dirty:
                return          # a racing flush already covered us
            self._dirty = False
            payload = self._state_payload()
            try:
                await tracing.run_in_executor(self._write_state, payload)
            except OSError:
                self._dirty = True
                raise

    def _apply_committed(self) -> None:
        while self.applied < self.commit:
            self.applied += 1
            pos = self.applied - self.snap["last_index"] - 1
            entry = self.entries[pos]
            cmd = entry["cmd"]
            v = int(cmd.get("max_volume_id", 0))
            if v > self.applied_value:
                self.applied_value = v
                self.adopt_max_volume_id(v)
            # fid reservation window: RELATIVE by construction — the
            # window is [applied_seq, applied_seq + n) at APPLY time,
            # so windows partition the id space in log order no matter
            # how stale the reserving leader's view was when it
            # appended (a new leader's first reservation always lands
            # ABOVE every window a deposed predecessor committed)
            n = int(cmd.get("seq_reserve", 0))
            if n > 0:
                start = self.applied_seq
                self.applied_seq = start + n
                self.adopt_seq_window(start, self.applied_seq,
                                      cmd.get("by", ""),
                                      int(entry.get("term", -1)))
            sm = cmd.get("shard_map")
            if sm is not None:
                self._apply_shard_map(sm)
        self._maybe_snapshot()
        self._update_gauges()

    def _apply_shard_map(self, sm: dict) -> None:
        """Shard-map transition at APPLY time: a compare-and-swap on
        the applied epoch. Like seq_reserve windows, the outcome is
        decided by LOG ORDER, not by the proposer's view — a deposed
        leader's proposal built on a stale epoch applies as a no-op,
        so two leaders can never interleave conflicting maps."""
        if int(sm.get("base", -1)) != self.applied_shard_epoch:
            return
        self.applied_shard_epoch += 1
        m = dict(sm.get("map") or {})
        m["epoch"] = self.applied_shard_epoch
        self.applied_shard = m
        self.adopt_shard_map(self.applied_shard_epoch, m)

    def _maybe_snapshot(self) -> None:
        """Log compaction (the reference's raft snapshot): fold applied
        entries into the snapshot once the log outgrows the threshold."""
        if len(self.entries) <= SNAPSHOT_THRESHOLD \
                or self.applied <= self.snap["last_index"]:
            return
        cut = self.applied - self.snap["last_index"]
        self.snap = {"last_index": self.applied,
                     "last_term": self._term_at(self.applied) or 0,
                     "value": self.applied_value,
                     "seq": self.applied_seq,
                     "shard_epoch": self.applied_shard_epoch,
                     "shard_map": self.applied_shard}
        self.entries = self.entries[cut:]
        self._mark_dirty()
        glog.info("%s: snapshot at index %d (value %d, %d entries kept)",
                  self.me, self.applied, self.applied_value,
                  len(self.entries))

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self.single:
            return
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=max(self.pulse * 2, 0.5)))
        self.frame_hub = FrameHub(ssl=tls.client_ctx(),
                                  jwt_key=self.jwt_key,
                                  request_timeout=self.attempt_timeout)
        self.last_pulse = time.monotonic()
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        # drain any dirt a cancelled replication round left behind.
        # Correctness never depends on this (every acted-on change was
        # flushed before the action), but a clean shutdown should not
        # discard a term bump it already observed.
        try:
            await self.flush()
        except OSError as e:
            glog.warning("%s: final raft-state flush failed: %s",
                         self.me, e)
        if self.frame_hub:
            await self.frame_hub.close()
        if self._http:
            await self._http.close()

    # ---- incoming RPCs (wired as HTTP handlers by MasterServer) ----

    def on_vote_request(self, term: int, candidate: str,
                        max_volume_id: int = 0,
                        last_log_index: int | None = None,
                        last_log_term: int | None = None) -> dict:
        if self.single:
            # a single-mode master is not part of any quorum; never let a
            # misconfigured peer demote it (it has no loop to recover)
            return {"term": self.term, "granted": False}
        if candidate == self.me:
            # our own vote request routed back to us through a peer-list
            # entry that is really our address: only the local self-vote
            # in _campaign counts
            return {"term": self.term, "granted": False}
        bumped = term > self.term
        if bumped:
            self.term = term
            self.voted_for = None
            self._step_down()
        # raft log-freshness rule: never elect a candidate whose log is
        # behind ours (it would reissue already-used volume ids). When
        # the candidate sends log coordinates use them; fall back to the
        # MaxVolumeId watermark for bare requests.
        if last_log_index is not None:
            fresh = ((last_log_term or 0, last_log_index)
                     >= (self.last_log_term(), self.last_index()))
        else:
            fresh = max_volume_id >= max(self.get_max_volume_id(),
                                         self.applied_value)
        granted = (term == self.term
                   and self.voted_for in (None, candidate)
                   and fresh)
        if granted:
            self.voted_for = candidate
            self.last_pulse = time.monotonic()
        if granted or bumped:
            self._mark_dirty()  # the handler flushes before replying
        self._update_gauges()
        return {"term": self.term, "granted": granted}

    def on_append(self, term: int, leader: str, prev_index: int,
                  prev_term: int, entries: list[dict],
                  leader_commit: int) -> dict:
        """AppendEntries: leader pulse + log replication + commit."""
        if self.single:
            return {"term": self.term, "ok": False}
        if term < self.term:
            return {"term": self.term, "ok": False,
                    "last": self.last_index()}
        if term > self.term:
            self.voted_for = None
            self.term = term
            self._mark_dirty()
        self.leader = leader
        if leader != self.me:
            self._step_down()
        self._note_leader(leader)
        self.last_pulse = time.monotonic()
        # consistency check at prev (entries already folded into the
        # snapshot are by definition committed => consistent)
        if prev_index < self.snap["last_index"]:
            drop = self.snap["last_index"] - prev_index
            entries = entries[drop:]
            prev_index = self.snap["last_index"]
            prev_term = self.snap["last_term"]
        pt = self._term_at(prev_index)
        if pt is None or pt != prev_term:
            return {"term": self.term, "ok": False,
                    "last": self.last_index()}
        changed = False
        for i, e in enumerate(entries):
            idx = prev_index + 1 + i
            have = self._term_at(idx)
            if have is None:
                self.entries.append(e)
                changed = True
            elif have != e["term"]:
                # conflict: truncate ours from idx on, take the leader's
                pos = idx - self.snap["last_index"] - 1
                del self.entries[pos:]
                self.entries.append(e)
                changed = True
        if changed:
            self._mark_dirty()
        match = prev_index + len(entries)
        if leader_commit > self.commit:
            self.commit = min(leader_commit, self.last_index())
            self._apply_committed()
        self._update_gauges()
        return {"term": self.term, "ok": True, "match": match}

    def on_install_snapshot(self, term: int, leader: str, last_index: int,
                            last_term: int, value: int,
                            seq: int = 0, shard_epoch: int = 0,
                            shard_map: dict | None = None) -> dict:
        """InstallSnapshot for followers whose log is behind the leader's
        compaction point."""
        if self.single or term < self.term:
            return {"term": self.term, "ok": False}
        if term > self.term:
            self.voted_for = None
            self.term = term
            # persist NOW, even when the snapshot turns out stale below:
            # currentTerm durability must not depend on installation, or
            # a restart forgets the bump and this node can double-vote
            self._mark_dirty()
        self.leader = leader
        self._step_down()
        self._note_leader(leader)
        self.last_pulse = time.monotonic()
        if last_index > self.last_index():
            self.snap = {"last_index": last_index, "last_term": last_term,
                         "value": value, "seq": seq,
                         "shard_epoch": shard_epoch,
                         "shard_map": shard_map}
            self.entries = []
            self.commit = self.applied = last_index
            if value > self.applied_value:
                self.applied_value = value
                self.adopt_max_volume_id(value)
            if seq > self.applied_seq:
                # folded reservation windows: adopt as foreign (by=""),
                # so the installing node fences its counter past them
                self.applied_seq = seq
                self.adopt_seq_window(0, seq, "", -1)
            if shard_epoch > self.applied_shard_epoch:
                # folded shard-map transitions: adopt the compacted map
                self.applied_shard_epoch = shard_epoch
                self.applied_shard = shard_map
                self.adopt_shard_map(shard_epoch, shard_map or {})
            self._mark_dirty()
        self._update_gauges()
        return {"term": self.term, "ok": True}

    # back-compat alias: the round-4 pulse RPC carried the value inline
    def on_leader_pulse(self, term: int, leader: str,
                        max_volume_id: int) -> dict:
        r = self.on_append(term, leader, self.last_index(),
                           self.last_log_term(), [], self.commit)
        if r.get("ok") and max_volume_id > self.applied_value:
            self.adopt_max_volume_id(max_volume_id)
        return r

    def _step_down(self) -> None:
        if self.role != self.FOLLOWER:
            glog.info("%s: stepping down from %s at term %d",
                      self.me, self.role, self.term)
            if self.role == self.LEADER:
                # journal only real depositions (candidate -> follower
                # happens every lost election and would flood the ring)
                events.record("raft_step_down", me=self.me,
                              term=self.term)
            self.role = self.FOLLOWER
            self._update_gauges()

    # ---- outgoing RPC transport (frames first, HTTP fallback) ----

    async def _raft_rpc(self, peer: str, path: str,
                        payload: dict) -> dict:
        """POST one raft RPC to `peer`, riding the persistent frame
        channel when the peer speaks it and dropping to the aiohttp
        session otherwise. The caller supplies the per-attempt
        wait_for; the channel deadline here bounds the frame leg so a
        refused/sick channel still leaves time for the HTTP leg."""
        if self.frame_hub is not None:
            try:
                # chaos site: force the frame leg down so chaos/ha
                # proves raft stays correct on the HTTP fallback
                await failpoints.fail("master.raft.frame")
                chan = self.frame_hub.get(target=peer)
                status, _, body = await chan.request(
                    "POST", path,
                    headers={"content-type": "application/json"},
                    body=json.dumps(payload).encode(),
                    timeout=self.attempt_timeout)
                if status == 200:
                    return json.loads(body)
            except (FrameChannelError, OSError, ValueError):
                pass    # breaker-open / severed / refused -> HTTP
        async with self._http.post(tls.url(peer, path),
                                   json=payload) as resp:
            return await resp.json()

    # ---- the election / heartbeat loop ----

    async def _loop(self) -> None:
        while True:
            if self.role == self.LEADER:
                await self._replicate_round()
                # leader lease: a leader partitioned from every peer must
                # stop serving writes before the others elect a successor,
                # or two masters assign volume ids concurrently
                if time.monotonic() - self._last_quorum \
                        > self.timeout_range[0] * 0.8:
                    self._step_down()
                    self.leader = None
                    self.last_pulse = time.monotonic()
                await asyncio.sleep(self.pulse)
            else:
                timeout = random.uniform(*self.timeout_range)
                await asyncio.sleep(self.pulse / 2)
                if time.monotonic() - self.last_pulse > timeout:
                    await self._campaign()

    async def _campaign(self) -> None:
        self.role = self.CANDIDATE
        self.term += 1
        term = self.term
        self.voted_for = self.me
        self._mark_dirty()
        await self.flush()   # self-vote durable before soliciting
        self.leader = None
        votes = 1  # self-vote

        async def ask(peer: str) -> bool:
            try:
                # chaos site: error/latency/drop model a dead, slow or
                # partitioned peer on the vote fan-out. The wait_for is
                # the per-ATTEMPT deadline: one hung peer socket (or an
                # armed latency) must not stretch this campaign past
                # the next election-timeout fire
                async def one() -> dict:
                    await failpoints.fail("master.vote")
                    return await self._raft_rpc(
                        peer, "/raft/vote",
                        {"term": term, "candidate": self.me,
                         "last_log_index": self.last_index(),
                         "last_log_term": self.last_log_term(),
                         "max_volume_id": self.get_max_volume_id()})
                body = await asyncio.wait_for(one(), self.attempt_timeout)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return False
            if body.get("term", 0) > self.term:
                self.term = body["term"]
                self.voted_for = None
                self._mark_dirty()
                self._step_down()
            return bool(body.get("granted"))

        results = await asyncio.gather(*(ask(p) for p in self.peers))
        votes += sum(results)
        if self.role == self.CANDIDATE and self.term == term \
                and votes >= self.majority:
            glog.info("%s: elected leader at term %d (%d/%d votes)",
                      self.me, term, votes, len(self.peers) + 1)
            self.role = self.LEADER
            self.leader = self.me
            self._note_leader(self.me)
            self._last_quorum = time.monotonic()
            # raft leader init: replicate from the end, learn backwards
            self.next_index = {p: self.last_index() + 1
                               for p in self.peers}
            self.match_index = {p: 0 for p in self.peers}
            self._update_gauges()
            await self._replicate_round()
        else:
            self._step_down()
            # reset the election timer: retrying immediately would keep
            # split candidates colliding in lockstep (the randomized
            # timeout only de-syncs them if both wait a fresh one)
            self.last_pulse = time.monotonic()
            self._update_gauges()

    async def _replicate_round(self) -> int:
        """One AppendEntries round to every peer: heartbeat, log catch-up
        (with InstallSnapshot below the compaction point), match/commit
        advancement, lease refresh. Returns acks incl. self."""

        async def send(peer: str) -> bool:
            ni = self.next_index.get(peer, self.last_index() + 1)
            try:
                if ni <= self.snap["last_index"]:
                    # peer is behind our compaction point. Chaos site:
                    # a dropped/failed InstallSnapshot models a
                    # partition mid-catch-up
                    async def snap_rpc() -> dict:
                        await failpoints.fail("master.snapshot")
                        return await self._raft_rpc(
                            peer, "/raft/snapshot",
                            {"term": self.term, "leader": self.me,
                             "last_index": self.snap["last_index"],
                             "last_term": self.snap["last_term"],
                             "value": self.snap["value"],
                             "seq": self.snap["seq"],
                             "shard_epoch": self.snap.get(
                                 "shard_epoch", 0),
                             "shard_map": self.snap.get("shard_map")})
                    reply = await asyncio.wait_for(snap_rpc(),
                                                   self.attempt_timeout)
                    if reply.get("term", 0) > self.term:
                        self._adopt_higher_term(reply["term"])
                        return False
                    if reply.get("ok"):
                        self.next_index[peer] = self.snap["last_index"] + 1
                        self.match_index[peer] = self.snap["last_index"]
                        return True
                    return False
                prev = ni - 1
                pos = prev - self.snap["last_index"]
                batch = self.entries[pos:]

                # chaos site: error/latency/drop on the AppendEntries
                # pulse — `drop` on a leader partitions it outbound, so
                # its lease expires while a successor gets elected (the
                # exact window tools/chaos.py ha arms). Per-attempt
                # deadline so one hung follower cannot stall the round
                # past the lease/pulse cadence.
                async def append_rpc() -> dict:
                    await failpoints.fail("master.append")
                    return await self._raft_rpc(
                        peer, "/raft/heartbeat",
                        {"term": self.term, "leader": self.me,
                         "prev_index": prev,
                         "prev_term": self._term_at(prev) or 0,
                         "entries": batch,
                         "commit": self.commit,
                         # legacy field so a mid-upgrade peer still
                         # adopts the watermark
                         "max_volume_id": self.get_max_volume_id()})
                reply = await asyncio.wait_for(append_rpc(),
                                               self.attempt_timeout)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return False
            if reply.get("term", 0) > self.term:
                self._adopt_higher_term(reply["term"])
                return False
            if reply.get("ok"):
                m = int(reply.get("match", prev + len(batch)))
                self.match_index[peer] = max(
                    self.match_index.get(peer, 0), m)
                self.next_index[peer] = self.match_index[peer] + 1
                return True
            # log mismatch: jump back using the follower's hint
            hint = int(reply.get("last", prev - 1))
            self.next_index[peer] = max(1, min(prev, hint + 1))
            return True  # the peer IS alive (acked the term)

        results = await asyncio.gather(*(send(p) for p in self.peers))
        acks = 1 + sum(results)
        if acks >= self.majority:
            self._last_quorum = time.monotonic()
        # quorum commit: largest N replicated on a majority with
        # log[N].term == currentTerm (the raft commit rule)
        if self.is_leader:
            matches = sorted(
                [self.last_index()]
                + [self.match_index.get(p, 0) for p in self.peers],
                reverse=True)
            n = matches[self.majority - 1]
            if n > self.commit and self._term_at(n) == self.term:
                self.commit = n
                self._apply_committed()
        # snapshot compaction / adopted-higher-term dirt from this
        # round becomes durable before the next round acts on it
        await self.flush()
        self._update_gauges()
        return acks

    def _adopt_higher_term(self, term: int) -> None:
        self.term = term
        self.voted_for = None
        self._mark_dirty()
        self._step_down()
        self._update_gauges()

    # ---- client surface ----

    async def append_command(self, cmd: dict,
                             rounds: int = 8) -> bool:
        """Leader-only: append `cmd` to the replicated log and drive
        replication until it commits (or this leader loses its standing).
        The reference raft-commits MaxVolumeIdCommand the same way before
        using a grown volume id (cluster_commands.go:23)."""
        if self.single:
            v = int(cmd.get("max_volume_id", 0))
            if v > self.applied_value:
                self.applied_value = v
            n = int(cmd.get("seq_reserve", 0))
            if n > 0:
                start = self.applied_seq
                self.applied_seq = start + n
                self.adopt_seq_window(start, self.applied_seq,
                                      cmd.get("by", ""), self.term)
            sm = cmd.get("shard_map")
            if sm is not None:
                self._apply_shard_map(sm)
            return True
        # serialize command commits: two interleaved append_command
        # drivers would race the per-peer next/match bookkeeping (and
        # their replication rounds would double-send suffixes)
        async with self._append_lock:
            if not self.is_leader:
                return False
            self.entries.append({"term": self.term, "cmd": cmd})
            self._mark_dirty()
            # the leader counts itself in the quorum, so its own log
            # entry must be durable before any peer acks are tallied
            await self.flush()
            idx = self.last_index()
            for _ in range(rounds):
                await self._replicate_round()
                if self.commit >= idx:
                    return True
                if not self.is_leader:
                    return False
                await asyncio.sleep(self.pulse / 4)
            return self.commit >= idx

    async def commit_max_volume_id(self) -> bool:
        """Synchronously replicate the current MaxVolumeId watermark to a
        quorum via the log; a value not acked by a majority may be lost
        on leader crash and reissued."""
        return await self.append_command(
            {"max_volume_id": self.get_max_volume_id()})
