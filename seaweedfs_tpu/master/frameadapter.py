"""Frame-protocol listener adapter for the MASTER.

The volume side terminates frames in server/frameserver.py over the
unified wire layer; the master has no wire layer — its handlers are
plain aiohttp coroutines. This adapter is the thin shim that lets the
control plane ride the same frame fabric: a connection opening with
the frame MAGIC on the master's public port (sniffed by
FastAssignProtocol) lands here, and each REQ frame is served by the
EXISTING aiohttp handler through a minimal request shim — so raft
durability rules (flush-before-reply), leader redirects (307 +
X-Raft-Leader), heartbeat delta publication and the assign path stay
wired exactly once.

Frame-served routes (everything else answers ``FLAG_FALLBACK`` and
the caller retries over HTTP):

* ``POST /raft/vote|/raft/heartbeat|/raft/snapshot`` — the raft mesh;
* ``POST /cluster/heartbeat`` — volume-server heartbeats;
* ``GET/POST /dir/lookup``, ``GET /dir/assign`` — the client hot path.

HELLO discipline matches the volume side: worker launch token or a
verified jwt identity claim; on a jwt-secured cluster an identity-less
HELLO is refused with GOAWAY before any request is served. The
-whiteList guard is applied per request exactly like the aiohttp
middleware (including the heartbeat-learned peer exemption on
/dir/lookup).
"""

from __future__ import annotations

import asyncio
import urllib.parse

from ..security.guard import path_guarded
from ..util import glog
from ..util.frame import (FLAG_FALLBACK, FrameDecoder, FrameError,
                          GOAWAY, HELLO, HELLO_OK, MAGIC, REQ, RESP,
                          encode_frame)

# (method, path) -> MasterServer handler attribute. Deliberately a
# closed whitelist: streaming responses (/cluster/watch), multipart
# (/submit) and the bulk of the debug surfaces stay aiohttp-only.
# /debug/traces is the one debug route admitted: cluster trace
# assembly (stats/introspect.py) pulls peer masters' span rings over
# the fabric, and its bounded-JSON body fits the frame contract.
_FRAME_ROUTES = {
    ("POST", "/raft/vote"): "h_raft_vote",
    ("POST", "/raft/heartbeat"): "h_raft_heartbeat",
    ("POST", "/raft/snapshot"): "h_raft_snapshot",
    ("POST", "/cluster/heartbeat"): "h_heartbeat",
    ("GET", "/dir/lookup"): "h_lookup",
    ("POST", "/dir/lookup"): "h_lookup",
    ("GET", "/dir/assign"): "h_assign",
    ("GET", "/debug/traces"): "h_traces",
}


class _ShimRequest:
    """The minimal aiohttp-Request surface the frame-served master
    handlers actually touch: .method/.path/.path_qs/.query/.headers/
    .remote plus async json()/read()/text()."""

    __slots__ = ("method", "path", "query", "headers", "remote",
                 "_body")

    def __init__(self, method: str, path: str, query: dict,
                 headers, remote: str | None, body: bytes) -> None:
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.remote = remote
        self._body = body

    @property
    def path_qs(self) -> str:
        if not self.query:
            return self.path
        return self.path + "?" + urllib.parse.urlencode(self.query)

    async def read(self) -> bytes:
        return self._body

    async def text(self) -> str:
        return self._body.decode(errors="replace")

    async def json(self):
        import json
        return json.loads(self._body or b"{}")


class MasterFrameProtocol(asyncio.Protocol):
    """Per-connection frame terminator for the master (control plane
    twin of server/frameserver.FrameServerProtocol)."""

    __slots__ = ("ms", "transport", "peer_ip", "dec", "hop", "authed",
                 "_hello", "_closed", "_tasks", "_write_lock", "_pre")

    def __init__(self, ms) -> None:
        self.ms = ms
        self.transport = None
        self.peer_ip: str | None = None
        self.dec = FrameDecoder()
        self.hop = False
        self.authed = False
        self._hello = False
        self._closed = False
        self._tasks: set = set()
        self._write_lock = asyncio.Lock()
        self._pre: bytearray | None = bytearray()

    # -- asyncio.Protocol --

    def connection_made(self, transport) -> None:
        self.transport = transport
        if not hasattr(self.ms, "_fast_conns"):
            self.ms._fast_conns = set()
        self.ms._fast_conns.add(transport)
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if isinstance(peer, tuple) and peer \
            else None

    def connection_lost(self, exc) -> None:
        self._closed = True
        getattr(self.ms, "_fast_conns", set()).discard(self.transport)
        for task in self._tasks:
            task.cancel()

    def data_received(self, data: bytes) -> None:
        if self._pre is not None:
            self._pre += data
            if self._pre[:1] == MAGIC[:1] and \
                    len(self._pre) < len(MAGIC) and \
                    MAGIC.startswith(bytes(self._pre)):
                return
            data = bytes(self._pre)
            self._pre = None
            if data.startswith(MAGIC):
                data = data[len(MAGIC):]
            if not data:
                return
        try:
            frames = self.dec.feed(data)
        except FrameError as e:
            glog.V(1).infof("master frame conn from %s: %s",
                            self.peer_ip, e)
            self._goaway(str(e))
            return
        for fr in frames:
            self._handle(fr)

    # -- dispatch --

    def _goaway(self, msg: str) -> None:
        if self._closed:
            return
        try:
            self.transport.write(encode_frame(GOAWAY, 0,
                                              {"error": msg}))
        except OSError:
            pass
        self._closed = True
        self.transport.close()

    def _verify_identity(self, ident: str) -> bool:
        key = getattr(self.ms, "jwt_key", "")
        if not key or not ident:
            return False
        from ..security.jwt import JwtError, decode_jwt
        from ..util.frame import HELLO_IDENTITY_FID
        try:
            return decode_jwt(key, ident).get(
                "fid") == HELLO_IDENTITY_FID
        except JwtError:
            return False

    def _hop_label(self) -> str:
        return "sibling" if (self.hop or self.peer_ip is None) \
            else "interhost"

    def _handle(self, fr) -> None:
        if not self._hello:
            if fr.type != HELLO:
                self._goaway("expected HELLO")
                return
            wc = self.ms.worker_ctx
            token = str(fr.meta.get("token", "") or "")
            self.hop = wc is not None and wc.token_ok(token)
            self.authed = self.hop or self._verify_identity(
                str(fr.meta.get("id", "") or ""))
            if getattr(self.ms, "jwt_key", "") and not self.authed:
                # same refusal the volume side gives: on jwt-secured
                # clusters no payload is served to an identity-less
                # connection
                self._goaway("hello identity required "
                             "(jwt-secured cluster)")
                return
            self._hello = True
            self.transport.write(encode_frame(
                HELLO_OK, fr.req_id,
                {"v": 1, "worker": wc.index if wc else 0}))
            return
        if fr.type != REQ:
            return
        task = asyncio.get_running_loop().create_task(self._serve(fr))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _serve(self, fr) -> None:
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FRAME_REQUESTS.labels(
                "server", self._hop_label()).inc()
        req_id = fr.req_id
        method = str(fr.meta.get("m", "GET")).upper()
        path = str(fr.meta.get("p", ""))
        query = fr.meta.get("q") or {}
        if not isinstance(query, dict):
            query = {}
        headers = {str(k).lower(): str(v)
                   for k, v in (fr.meta.get("h") or {}).items()}
        handler_name = _FRAME_ROUTES.get((method, path))
        if handler_name is None:
            await self._send_fallback(req_id)
            return
        ms = self.ms
        # the aiohttp guard middleware, replayed: guarded paths check
        # -whiteList against the real peer; /dir/lookup admits
        # heartbeat-learned cluster members
        guarded = path_guarded(path, ms._GUARDED) and not (
            path == "/dir/lookup" and ms._is_peer(self.peer_ip))
        if guarded and not ms.guard.empty \
                and not ms.guard.allows(self.peer_ip):
            await self._send_json(req_id, 401, {},
                                  b'{"error": "ip not in whitelist"}')
            return
        shim = _ShimRequest(method, path, query, headers,
                            self.peer_ip, fr.payload)
        try:
            resp = await getattr(ms, handler_name)(shim)
        except asyncio.CancelledError:
            raise
        except Exception as e:      # a handler bug must not wedge
            glog.warning("master frame %s %s: %s: %s", method, path,
                         type(e).__name__, e)
            await self._send_json(
                req_id, 500, {},
                b'{"error": "internal frame handler error"}')
            return
        hdrs = {k: v for k, v in resp.headers.items()
                if k.lower() not in ("content-length", "content-type",
                                     "date", "server")}
        body = resp.body
        if body is None:
            body = b""
        elif not isinstance(body, (bytes, bytearray)):
            body = bytes(body)
        await self._send_json(req_id, resp.status, hdrs, bytes(body),
                              ct=resp.content_type or
                              "application/json")

    # -- response rendering --

    async def _send_fallback(self, req_id: int) -> None:
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FRAME_FALLBACKS.labels(self._hop_label()).inc()
        async with self._write_lock:
            if not self._closed:
                self.transport.write(encode_frame(
                    RESP, req_id, {"s": 421}, flags=FLAG_FALLBACK))

    async def _send_json(self, req_id: int, status: int, headers: dict,
                         body: bytes,
                         ct: str = "application/json") -> None:
        meta = {"s": status, "h": headers, "ct": ct}
        async with self._write_lock:
            if not self._closed:
                self.transport.write(
                    encode_frame(RESP, req_id, meta, body))
