"""Monotonic needle-key sequencer (reference: weed/sequence/sequence.go,
memory_sequencer.go; the etcd-backed variant maps to a pluggable subclass).

``RaftSequencer`` is the HA variant: under ``-peers`` the quorum log IS
the durable shared allocator — the leader raft-commits relative
reservation windows (``seq_reserve`` commands) and only ever hands out
ids inside a window its own committed log owns, so a deposed leader and
its successor can never issue the same file id (see the class docstring
for the fencing argument).
"""

from __future__ import annotations

import asyncio
import os
import threading

from ..util import failpoints


class SequenceBehind(Exception):
    """The committed reservation window cannot cover the requested id
    block — the caller must raft-reserve a fresh window (leader) or
    redirect to whoever can (follower)."""


class SequenceUnavailable(Exception):
    """No reservation window could be committed: this master is not the
    quorum leader (or lost its standing mid-reserve)."""


class MemorySequencer:
    """In-memory monotonic allocator; synced up from volume-server
    heartbeats reporting their max file key (master_grpc_server.go)."""

    def __init__(self, start: int = 1):
        self._counter = max(1, start)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        """Allocate `count` consecutive ids; returns the first."""
        with self._lock:
            first = self._counter
            self._counter += count
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class FileSequencer(MemorySequencer):
    """Crash-safe batched allocator: checkpoints `counter + step` to a
    file and only touches disk every `step` allocations.

    The durability model of the reference's EtcdSequencer
    (etcd_sequencer.go:34-135, batch step 100): after a restart the
    counter resumes from the checkpoint, which is always >= any id ever
    handed out, so ids are never reissued (a gap of up to `step` ids is
    the accepted cost).
    """

    def __init__(self, path: str, step: int = 100):
        self.path = path
        self.step = step
        start = 1
        if os.path.exists(path):
            # a corrupt checkpoint must be fatal: silently restarting at 1
            # would reissue every id ever handed out and overwrite needles
            try:
                with open(path) as f:
                    start = int(f.read().strip())
            except (OSError, ValueError) as e:
                raise SystemExit(
                    f"sequencer checkpoint {path} unreadable/corrupt: {e}; "
                    f"repair or remove it explicitly") from e
        super().__init__(start)
        self._ceiling = start  # all ids < ceiling are checkpointed as used

    def _reserve_locked(self, need: int) -> None:
        """Ensure the checkpoint covers all ids < max(need, counter)+1;
        only writes when the counter crosses the ceiling — i.e. once per
        `step` allocations, not per call."""
        if need > self._ceiling:
            self._ceiling = need + self.step
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self._ceiling))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            first = self._counter
            self._counter += count
            self._reserve_locked(self._counter)
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1
                self._reserve_locked(self._counter)


class EtcdSequencer:  # pragma: no cover - driver-gated (no etcd in image)
    """etcd-backed batched allocator (etcd_sequencer.go:34-135): a CAS
    loop reserves [start, start+step) under a well-known key; only every
    `step` allocations touch etcd."""

    KEY = "/seaweedfs_tpu/max_file_id"

    def __init__(self, endpoints: str, step: int = 100):
        try:
            import etcd3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "etcd sequencer needs the etcd3 client installed") from e
        import etcd3
        host, _, port = endpoints.split(",")[0].partition(":")
        # explicit per-request deadline: python-etcd3 defaults to NO
        # timeout, so a wedged etcd would wedge every id reservation
        self._client = etcd3.client(host=host, port=int(port or 2379),
                                    timeout=10)
        self.step = step
        self._lock = threading.Lock()
        self._counter = 0
        self._ceiling = 0

    def _reserve_locked(self, need: int) -> None:
        """CAS-extend the etcd checkpoint until it covers `need` ids."""
        # chaos site: a wedged/failed etcd reservation surfaces as a
        # bounded assign error, never a silently reused id block
        failpoints.sync_fail("master.etcd")
        tx = self._client.transactions
        while self._ceiling < need:
            raw, _ = self._client.get(self.KEY)
            cur = int(raw or 0)
            new = max(cur, need, self._counter) + self.step
            if raw is None:
                # create-if-absent: version==0 compare makes two fresh
                # masters race safely (one wins, the other retries)
                ok, _ = self._client.transaction(
                    compare=[tx.version(self.KEY) == 0],
                    success=[tx.put(self.KEY, str(new))],
                    failure=[])
            else:
                ok, _ = self._client.transaction(
                    compare=[tx.value(self.KEY) == raw],
                    success=[tx.put(self.KEY, str(new))],
                    failure=[])
            if ok:
                self._counter = max(self._counter, cur, 1)
                self._ceiling = new

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            # reserve FIRST: it may raise _counter to the etcd checkpoint
            # (ids below it were issued by a previous life or a peer);
            # computing `first` before would reissue them
            self._reserve_locked(max(self._counter, 1) + count)
            first = max(self._counter, 1)
            if first + count > self._ceiling:
                self._reserve_locked(first + count)
            self._counter = first + count
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class RaftSequencer:
    """Quorum-committed file-id allocator (multi-master ``-peers``).

    Wraps any local sequencer and gates every allocation on a
    raft-committed reservation window:

    * the leader appends ``{"seq_reserve": n, "by": me}`` through
      ``Election.append_command`` and hands out ids only after the
      entry reaches commit index — ids are NEVER issued from an
      uncommitted reservation;
    * the window is RELATIVE: at apply time it becomes
      ``[applied_seq, applied_seq + n)``, so windows partition the id
      space in strict log order no matter how stale the reserving
      leader's view was — a successor's first window always starts
      above every window any deposed predecessor committed;
    * a node claims a window for local allocation ONLY when it is the
      author (``by == me``), the entry's term is its current term and
      it still leads — every foreign window instead fences the local
      counter past its end, so a follower promoted later starts above
      everything ever reserved;
    * a deposed leader may keep draining its already-committed window
      (those ids live in the successor's committed log too — exactly
      the acceptance contract), but the moment the window is spent it
      gets no new one and the caller redirects.

    Unissued ids in abandoned windows are simply burned — file keys
    are sparse by design (same contract as the lease blocks the
    ``-workers`` assign accelerators already abandon).
    """

    STEP = 4096                 # ids per reservation round trip

    def __init__(self, inner, election, step: int = STEP):
        self.inner = inner
        self.election = election
        self.step = step
        # exclusive end of the newest APPLIED reservation window; the
        # local counter sits inside [start, ceiling) only while a
        # window claimed by THIS node's current leadership is open
        self.ceiling = election.applied_seq
        self.inner.set_max(self.ceiling - 1)
        self.reserves = 0           # committed windows this process won
        self._reserve_lock = asyncio.Lock()
        election.adopt_seq_window = self.adopt_window

    # -- applied-state hook (runs at commit index on every node) -------

    def adopt_window(self, start: int, end: int, by: str,
                     term: int) -> None:
        if end <= self.ceiling:
            return
        self.ceiling = end
        if by == self.election.me and term == self.election.term \
                and self.election.is_leader:
            # our own freshly committed window: open it for local
            # allocation (counter may already sit inside it when
            # heartbeat set_max pushed past the start)
            self.inner.set_max(start - 1)
            self.reserves += 1
        else:
            # a window some other leadership committed: fence the
            # counter past it so this node can never re-issue from it
            self.inner.set_max(end - 1)

    # -- allocation ----------------------------------------------------

    def next_file_id(self, count: int = 1) -> int:
        """Allocate `count` consecutive ids inside the open committed
        window; raises :class:`SequenceBehind` when the window cannot
        cover the block (callers reserve, then retry)."""
        if self.inner.peek() + count > self.ceiling:
            raise SequenceBehind(
                f"window exhausted at {self.ceiling}")
        first = self.inner.next_file_id(count)
        if first + count > self.ceiling:
            # a racing set_max moved the counter past the window edge
            # mid-allocation: burn the block, never hand out ids above
            # the committed ceiling
            raise SequenceBehind(
                f"window burned at {self.ceiling}")
        return first

    async def reserve(self, count: int = 1) -> bool:
        """Leader-only: raft-commit a window covering at least `count`
        more ids. True when the window is committed AND claimed locally
        (a True return makes the next ``next_file_id(count)`` succeed
        barring racing ``set_max`` bumps)."""
        async with self._reserve_lock:
            # a queued waiter may find the window it needs already
            # committed by the reserve it queued behind
            if self.inner.peek() + count <= self.ceiling:
                return True
            # the window must cover `count` ids FROM ITS OWN START: the
            # claim fences the counter to the window start, so sizing
            # it only by the counter's current distance past the old
            # ceiling would under-reserve any count > step and fail the
            # leader's own assign forever. The peek()-based term still
            # covers a heartbeat watermark that jumped the counter far
            # past every committed window.
            need = max(self.step, count,
                       self.inner.peek() + count - self.ceiling)
            ok = await self.election.append_command(
                {"seq_reserve": need, "by": self.election.me})
            # committed AND applied locally => adopt_window ran; the
            # claim check is the window actually being usable (an
            # entry committed by a SUCCESSOR after we lost the term
            # applies as foreign and leaves the counter fenced)
            return bool(ok) and \
                self.inner.peek() + count <= self.ceiling

    # -- passthrough (heartbeat watermark / UI) ------------------------

    def set_max(self, seen: int) -> None:
        self.inner.set_max(seen)

    def peek(self) -> int:
        return self.inner.peek()
