"""Monotonic needle-key sequencer (reference: weed/sequence/sequence.go,
memory_sequencer.go; the etcd-backed variant maps to a pluggable subclass).
"""

from __future__ import annotations

import threading


class MemorySequencer:
    """In-memory monotonic allocator; synced up from volume-server
    heartbeats reporting their max file key (master_grpc_server.go)."""

    def __init__(self, start: int = 1):
        self._counter = max(1, start)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        """Allocate `count` consecutive ids; returns the first."""
        with self._lock:
            first = self._counter
            self._counter += count
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter
