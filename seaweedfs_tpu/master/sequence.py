"""Monotonic needle-key sequencer (reference: weed/sequence/sequence.go,
memory_sequencer.go; the etcd-backed variant maps to a pluggable subclass).
"""

from __future__ import annotations

import os
import threading


class MemorySequencer:
    """In-memory monotonic allocator; synced up from volume-server
    heartbeats reporting their max file key (master_grpc_server.go)."""

    def __init__(self, start: int = 1):
        self._counter = max(1, start)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        """Allocate `count` consecutive ids; returns the first."""
        with self._lock:
            first = self._counter
            self._counter += count
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class FileSequencer(MemorySequencer):
    """Crash-safe batched allocator: checkpoints `counter + step` to a
    file and only touches disk every `step` allocations.

    The durability model of the reference's EtcdSequencer
    (etcd_sequencer.go:34-135, batch step 100): after a restart the
    counter resumes from the checkpoint, which is always >= any id ever
    handed out, so ids are never reissued (a gap of up to `step` ids is
    the accepted cost).
    """

    def __init__(self, path: str, step: int = 100):
        self.path = path
        self.step = step
        start = 1
        if os.path.exists(path):
            # a corrupt checkpoint must be fatal: silently restarting at 1
            # would reissue every id ever handed out and overwrite needles
            try:
                with open(path) as f:
                    start = int(f.read().strip())
            except (OSError, ValueError) as e:
                raise SystemExit(
                    f"sequencer checkpoint {path} unreadable/corrupt: {e}; "
                    f"repair or remove it explicitly") from e
        super().__init__(start)
        self._ceiling = start  # all ids < ceiling are checkpointed as used

    def _reserve_locked(self, need: int) -> None:
        """Ensure the checkpoint covers all ids < max(need, counter)+1;
        only writes when the counter crosses the ceiling — i.e. once per
        `step` allocations, not per call."""
        if need > self._ceiling:
            self._ceiling = need + self.step
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self._ceiling))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            first = self._counter
            self._counter += count
            self._reserve_locked(self._counter)
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1
                self._reserve_locked(self._counter)


class EtcdSequencer:  # pragma: no cover - driver-gated (no etcd in image)
    """etcd-backed batched allocator (etcd_sequencer.go:34-135): a CAS
    loop reserves [start, start+step) under a well-known key; only every
    `step` allocations touch etcd."""

    KEY = "/seaweedfs_tpu/max_file_id"

    def __init__(self, endpoints: str, step: int = 100):
        try:
            import etcd3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "etcd sequencer needs the etcd3 client installed") from e
        import etcd3
        host, _, port = endpoints.split(",")[0].partition(":")
        # explicit per-request deadline: python-etcd3 defaults to NO
        # timeout, so a wedged etcd would wedge every id reservation
        self._client = etcd3.client(host=host, port=int(port or 2379),
                                    timeout=10)
        self.step = step
        self._lock = threading.Lock()
        self._counter = 0
        self._ceiling = 0

    def _reserve_locked(self, need: int) -> None:
        """CAS-extend the etcd checkpoint until it covers `need` ids."""
        tx = self._client.transactions
        while self._ceiling < need:
            raw, _ = self._client.get(self.KEY)
            cur = int(raw or 0)
            new = max(cur, need, self._counter) + self.step
            if raw is None:
                # create-if-absent: version==0 compare makes two fresh
                # masters race safely (one wins, the other retries)
                ok, _ = self._client.transaction(
                    compare=[tx.version(self.KEY) == 0],
                    success=[tx.put(self.KEY, str(new))],
                    failure=[])
            else:
                ok, _ = self._client.transaction(
                    compare=[tx.value(self.KEY) == raw],
                    success=[tx.put(self.KEY, str(new))],
                    failure=[])
            if ok:
                self._counter = max(self._counter, cur, 1)
                self._ceiling = new

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            # reserve FIRST: it may raise _counter to the etcd checkpoint
            # (ids below it were issued by a previous life or a peer);
            # computing `first` before would reissue them
            self._reserve_locked(max(self._counter, 1) + count)
            first = max(self._counter, 1)
            if first + count > self._ceiling:
                self._reserve_locked(first + count)
            self._counter = first + count
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen + 1 > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter
