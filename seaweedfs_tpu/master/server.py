"""Master server: cluster control plane.

Reference: weed/server/master_server.go (HTTP /dir/assign, /dir/lookup,
status), master_grpc_server.go (heartbeat stream -> topology sync + pubsub
of location deltas), master_server_handlers.go:96-137 (assign + on-demand
volume growth). gRPC streams become HTTP POST heartbeats + an SSE watch
stream over the asyncio mesh.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from aiohttp import web
import aiohttp

from .. import qos
from ..pb import messages as pb
from ..storage import types as t
from ..storage.super_block import ReplicaPlacement
from ..topology.layout import (LayoutKey, PlacementError, VolumeLayout,
                               find_empty_slots)
from ..topology.tree import DataNode, Topology
from ..security import tls
from ..util import failpoints, glog, tracing
from .election import Election
from .sequence import (MemorySequencer, RaftSequencer, SequenceBehind,
                       SequenceUnavailable)


class MasterServer:
    def __init__(self, ip: str = "127.0.0.1", port: int = 9333,
                 volume_size_limit_mb: int = 30_000,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 jwt_key: str = "",
                 peers: list[str] | None = None,
                 election_timeout: tuple[float, float] = (1.0, 2.0),
                 election_pulse: float = 0.3,
                 sequencer: str = "memory",
                 meta_dir: str = "",
                 maintenance_interval_s: float = 900.0,
                 admin_scripts: list[str] | None = None,
                 admin_scripts_interval_s: float = 17 * 60.0,
                 white_list: list[str] | None = None,
                 volume_preallocate: bool = False,
                 autopilot_interval_s: float = 0.0,
                 autopilot_mbps: float = 16.0,
                 autopilot_dryrun: bool = False,
                 autopilot_concurrency: int = 2,
                 autopilot_tier_backend: str = "",
                 worker_ctx=None):
        # -workers N (server/workers.py): this master is the PRIMARY
        # (worker 0) of a fleet whose other members are assign
        # accelerators sharing the public port via SO_REUSEPORT
        self.worker_ctx = worker_ctx
        from ..security.guard import Guard
        # -whiteList: IP guard on the API surface (guard.go:43-137,
        # wrapped handlers at master_server.go:110-120)
        self.guard = Guard(white_list or ())
        # -volumePreallocate (master.go:51): grown volumes fallocate
        # their full size limit up front
        self.volume_preallocate = volume_preallocate
        self.ip = ip
        self.port = port
        self._peers = list(peers or [])
        self._election_timeout = election_timeout
        self._election_pulse = election_pulse
        # -mdir: raft-state directory (reference -mdir, raft_server.go:60)
        self.meta_dir = meta_dir
        if meta_dir:
            os.makedirs(meta_dir, exist_ok=True)
        self.election: Election | None = None
        self.jwt_key = jwt_key
        self.volume_size_limit = volume_size_limit_mb * 1024 * 1024
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        # automatic maintenance (master_server.go:186-250 startAdminScripts
        # + topology_event_handling.go:22-28 auto-vacuum): leader-only
        # background upkeep so an unattended cluster reclaims space and
        # runs its configured admin scripts. 0 disables either loop.
        self.maintenance_interval_s = maintenance_interval_s
        self.admin_scripts = [s.strip() for s in (admin_scripts or [])
                              if s.strip()]
        self.admin_scripts_interval_s = admin_scripts_interval_s
        self.topo = Topology(pulse_seconds=pulse_seconds)
        # -sequencer memory | file:<path> | etcd:<host:port>
        # (master.toml [master.sequencer], scaffold.go:362-371)
        if sequencer.startswith("file:"):
            from .sequence import FileSequencer
            self.seq = FileSequencer(sequencer[5:])
        elif sequencer.startswith("etcd:"):
            from .sequence import EtcdSequencer
            self.seq = EtcdSequencer(sequencer[5:])
        else:
            # under -peers the raft log itself is the durable shared
            # allocator: _make_election wraps this in a RaftSequencer,
            # so even the in-memory sequencer is failover-safe (every
            # issued id sits inside a quorum-committed window)
            self.seq = MemorySequencer()
        self.layouts: dict[LayoutKey, VolumeLayout] = {}
        self._watchers: list[asyncio.Queue] = []
        self._peer_ips: set[str] = set()
        self._runner: web.AppRunner | None = None
        self._site: web.TCPSite | None = None
        self._tasks: list[asyncio.Task] = []
        self._http: aiohttp.ClientSession | None = None
        # lazily-built frame hub for cluster-scope introspection
        # fan-out (stats/introspect.py) — frame-first, HTTP fallback
        self._introspect_hub = None
        self._grow_lock = asyncio.Lock()
        # applied filer shard map mirror (filer/shard.py): fed by the
        # election's adopt hook at APPLY time; served on /cluster/shards
        self.shard_epoch = 0
        self.shard_map: dict | None = None
        # autopilot maintenance plane (autopilot/): the object always
        # exists so POST /debug/autopilot?run=1 can force a cycle even
        # with the loop disabled; the loop itself is leader-only and
        # starts in start() when -autopilot.interval > 0
        from ..autopilot import Autopilot
        self.autopilot = Autopilot(
            self, interval_s=autopilot_interval_s,
            mbps=autopilot_mbps, dryrun=autopilot_dryrun,
            concurrency=autopilot_concurrency,
            tier_backend=autopilot_tier_backend,
            garbage_threshold=garbage_threshold)
        # bandwidth arbiter adoption (-qos.mbps): autopilot repair
        # pacing yields to cluster foreground pressure (volume nodes
        # report theirs through heartbeats) down to the floor
        arb = qos.arbiter()
        if arb is not None:
            self.autopilot.executor.bucket = arb.adopt(
                "autopilot", self.autopilot.executor.bucket)
        self.app = self._build_app()

    # ------------------------------------------------------------------
    # the client-API paths the reference wraps with guard.WhiteList
    # (master_server.go:110-120). Deliberately NOT guarded: the UI, the
    # fid redirect, the raft/heartbeat/watch mesh (mTLS-scoped instead).
    # /dir/lookup IS guarded like the reference's master_server.go:111 —
    # volume servers calling it during replica fan-out are auto-admitted
    # by _is_peer (their IP is learned from heartbeats), so an operator
    # whitelist only needs to cover clients. Follower control routes
    # 307-redirect (the client IP is judged here, on the leader); only
    # /submit still proxies, so peer master IPs need whitelisting for
    # that route alone.
    _GUARDED = ("/dir/assign", "/dir/lookup", "/dir/status",
                "/col/delete", "/vol/grow", "/vol/status", "/vol/vacuum",
                "/vol/volumes", "/vol/ec_lookup", "/submit", "/stats/")

    def _is_peer(self, ip: str | None) -> bool:
        """Heartbeating volume servers are cluster members, not clients;
        admit them on guarded paths regardless of -whiteList."""
        return ip is not None and ip in self._peer_ips

    def _remote(self, req: web.Request) -> str | None:
        """The peer IP a policy decision should see: for an intra-host
        worker hop (launch-token authenticated) the accelerator's
        X-Forwarded-For carries the real client address."""
        wc = self.worker_ctx
        if wc is not None:
            from ..server import workers as wk
            if wc.token_ok(req.headers.get(wk.WORKER_HEADER)):
                return req.headers.get(wk.FORWARDED_HEADER) or req.remote
        return req.remote

    def _worker_auth(self, req: web.Request) -> bool:
        """Gate on the internal mesh endpoints (/cluster/seq_lease,
        /cluster/assign_state): when a worker token is configured only
        fleet members holding it get in; a standalone master leaves
        them open like the rest of the /cluster mesh (mTLS-scoped)."""
        wc = self.worker_ctx
        if wc is None or not wc.token:
            return True
        from ..server import workers as wk
        return wc.token_ok(req.headers.get(wk.WORKER_HEADER))

    def _build_app(self) -> web.Application:
        from ..security.guard import middleware as guard_mw
        from ..security.guard import path_guarded
        app = web.Application(
            client_max_size=64 * 1024 * 1024,
            middlewares=[guard_mw(
                lambda: self.guard,
                lambda req: (path_guarded(req.path, self._GUARDED)
                             and not (req.path == "/dir/lookup"
                                      and self._is_peer(
                                          self._remote(req)))),
                remote_of=self._remote)])
        app.router.add_route("*", "/dir/assign", self.h_assign)
        app.router.add_route("*", "/dir/lookup", self.h_lookup)
        app.router.add_get("/dir/status", self.h_dir_status)
        app.router.add_get("/cluster/status", self.h_cluster_status)
        app.router.add_post("/cluster/heartbeat", self.h_heartbeat)
        app.router.add_get("/cluster/watch", self.h_watch)
        app.router.add_get("/cluster/seq_lease", self.h_seq_lease)
        app.router.add_get("/cluster/assign_state", self.h_assign_state)
        app.router.add_route("*", "/cluster/shards", self.h_cluster_shards)
        app.router.add_get("/debug/shards", self.h_debug_shards)
        app.router.add_get("/stats/health", self.h_health)
        app.router.add_get("/metrics", self.h_metrics)
        app.router.add_route("*", "/debug/failpoints",
                             failpoints.handle_debug)
        # flight recorder: shared handler trio (stats/timeline.py), so
        # the master serves the same /debug/timeline//events//health
        # contract as every data-plane daemon
        from ..stats.timeline import recorder_handlers
        h_tl, h_ev, h_hl = recorder_handlers()
        app.router.add_get("/debug/timeline", h_tl)
        app.router.add_post("/debug/timeline", h_tl)
        app.router.add_get("/debug/events", h_ev)
        app.router.add_get("/debug/health", h_hl)
        # span ring + in-flight table: instance ATTRIBUTES (not
        # closures in the router) because the frame adapter whitelist
        # resolves handlers by getattr — peer masters pull trace spans
        # over the fabric
        self.h_traces, self.h_trace_requests = tracing.debug_handlers()
        app.router.add_get("/debug/traces", self.h_traces)
        app.router.add_get("/debug/requests", self.h_trace_requests)
        from ..stats import profiler
        from ..util import pprof
        app.router.add_get("/debug/profile", profiler.debug_handler())
        app.router.add_get("/debug/pprof", pprof.debug_handler())
        # cluster scope: leader-side fan-out over every known member
        # (multi-segment paths can't collide with the /{fid} catch-all)
        app.router.add_get("/debug/cluster", self.h_cluster_index)
        app.router.add_get("/debug/cluster/trace/{tid}",
                           self.h_cluster_trace)
        app.router.add_get("/debug/cluster/timeline",
                           self.h_cluster_timeline)
        app.router.add_get("/debug/cluster/events",
                           self.h_cluster_events)
        app.router.add_get("/debug/cluster/health",
                           self.h_cluster_health)
        app.router.add_route("*", "/debug/autopilot", self.h_autopilot)
        app.router.add_get("/debug/qos", qos.debug_handler)
        app.router.add_route("*", "/vol/grow", self.h_grow)
        app.router.add_route("*", "/vol/vacuum", self.h_vacuum)
        app.router.add_route("*", "/col/delete", self.h_collection_delete)
        app.router.add_get("/vol/volumes", self.h_volumes)
        app.router.add_get("/vol/status", self.h_volumes)
        app.router.add_get("/vol/ec_lookup", self.h_ec_lookup)
        app.router.add_route("*", "/submit", self.h_submit)
        app.router.add_post("/raft/vote", self.h_raft_vote)
        app.router.add_post("/raft/heartbeat", self.h_raft_heartbeat)
        app.router.add_post("/raft/snapshot", self.h_raft_snapshot)
        app.router.add_get("/ui", self.h_ui)
        app.router.add_get("/", self.h_ui)
        # catch-all LAST: GET /<fid> redirects to a holder of the volume
        # (master_server.go:121 redirectHandler)
        app.router.add_get("/{fid}", self.h_fid_redirect)
        return app

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def frame_protocol(self):
        """Factory for the master-side frame terminator — the hook
        FastAssignProtocol's MAGIC sniff upgrades onto (the assign
        accelerator has no such surface, so frames there degrade to
        HTTP)."""
        from .frameadapter import MasterFrameProtocol
        return MasterFrameProtocol(self)

    async def start(self) -> None:
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=30))
        # multi-master: the raft state MUST be replayed (executor —
        # this loop may already serve sibling daemons) BEFORE any
        # listener goes live. A respawned worker that answered
        # /dir/assign with `self.election is None` would claim
        # leadership with no raft state next to the real leader.
        # Single mode defers until the port is bound (identity may be
        # an ephemeral :0 here, and single mode is leader by fiat).
        if self._peers:
            await self._make_election()
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        # public listener: /dir/assign answered straight off the socket,
        # everything else upgrades in place onto the aiohttp app
        from ..server.fasthttp import FastAssignProtocol
        loop = asyncio.get_running_loop()
        wc = self.worker_ctx
        self._server = await loop.create_server(
            lambda: FastAssignProtocol(self), self.ip, self.port,
            ssl=tls.server_ctx(), reuse_address=True,
            reuse_port=wc is not None)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        if wc is not None:
            # a private listener is the direct door to THIS process for
            # the assign accelerators (lease/assign-state/proxy target).
            # Plain aiohttp, NOT the raw fast path: a proxied
            # /dir/assign must be guarded against the forwarded client
            # IP (token + X-Forwarded-For via _remote()), which the
            # header-blind raw protocol cannot see — it would judge the
            # accelerator's loopback address instead
            self._priv_server = await loop.create_server(
                self._runner.server, self.ip, 0,
                ssl=tls.server_ctx(), reuse_address=True)
            priv_port = self._priv_server.sockets[0].getsockname()[1]
            wc.write_state(ip=self.ip, port=priv_port, role="master")
        if self.election is None:
            await self._make_election()
        await self.election.start()
        self._tasks.append(asyncio.create_task(self._liveness_loop()))
        if self.autopilot.interval_s > 0:
            # long-lived leader-only maintenance loop; handle retained
            # and cancelled in stop() (orphan-task discipline)
            self._tasks.append(asyncio.create_task(self.autopilot.run()))
        if self.maintenance_interval_s > 0:
            self._tasks.append(
                asyncio.create_task(self._auto_vacuum_loop()))
        if self.admin_scripts and self.admin_scripts_interval_s > 0:
            self._tasks.append(
                asyncio.create_task(self._admin_scripts_loop()))

    async def stop(self) -> None:
        if self.election:
            await self.election.stop()
        for task in self._tasks:
            task.cancel()
        if self._http:
            await self._http.close()
        if self._introspect_hub is not None:
            await self._introspect_hub.close()
        if getattr(self, "_server", None) is not None:
            self._server.close()
            # NOT wait_closed() (3.12 waits on live keep-alives)
            for tr in list(getattr(self, "_fast_conns", ())):
                tr.close()
        if getattr(self, "_priv_server", None) is not None:
            self._priv_server.close()
        if self._runner:
            await self._runner.cleanup()

    _assign_ctr = None

    def count_assign(self) -> None:
        """Cached assign counter for the fast path."""
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        if self._assign_ctr is None:
            self._assign_ctr = \
                metrics.MASTER_ASSIGN_REQUESTS.labels("ok")
        self._assign_ctr.inc()

    # ---- layouts ----

    def _layout(self, collection: str, replication: str,
                ttl: str) -> VolumeLayout:
        replication = str(ReplicaPlacement.parse(
            replication or self.default_replication))
        key = LayoutKey(collection, replication, str(t.TTL.parse(ttl)))
        lay = self.layouts.get(key)
        if lay is None:
            lay = VolumeLayout(key, self.volume_size_limit)
            self.layouts[key] = lay
        return lay

    def _refresh_writable(self, node: DataNode) -> None:
        for m in node.volumes.values():
            rp = ReplicaPlacement.from_byte(m.replica_placement)
            ttl = str(t.TTL.from_uint32(m.ttl))
            lay = self._layout(m.collection, str(rp), ttl)
            writable = (not m.read_only
                        and m.size < self.volume_size_limit)
            lay.set_writable(m.id, writable)

    # ---- leadership ----

    @property
    def is_leader(self) -> bool:
        return self.election is None or self.election.is_leader

    @property
    def leader_url(self) -> str | None:
        return self.url if self.election is None else self.election.leader

    def _adopt_max_volume_id(self, v: int) -> None:
        """Follower side of the one replicated raft value
        (cluster_commands.go:23 MaxVolumeIdCommand)."""
        self.topo.max_volume_id = max(self.topo.max_volume_id, v)

    async def _make_election(self) -> None:
        """Build the Election (its ctor replays persisted raft state
        from disk, so it runs on the executor — under `weed-tpu
        server` and worker respawn this loop already serves traffic)
        and wire the MaxVolumeId exchange hooks."""
        self.election = await tracing.run_in_executor(lambda: Election(
            self.url, self._peers,
            election_timeout=self._election_timeout,
            pulse=self._election_pulse,
            state_path=(os.path.join(self.meta_dir, "raft_state.json")
                        if self.meta_dir else None),
            jwt_key=self.jwt_key))
        self.election.get_max_volume_id = lambda: self.topo.max_volume_id
        self.election.adopt_max_volume_id = self._adopt_max_volume_id
        self.election.adopt_shard_map = self._adopt_shard_map
        # replayed raft state may already hold a committed map
        self.shard_epoch = self.election.applied_shard_epoch
        self.shard_map = self.election.applied_shard
        if self._peers and not isinstance(self.seq, RaftSequencer):
            # multi-master: every fid block must come out of a
            # quorum-committed reservation window — the raft log is
            # the shared durable allocator (wrapping the configured
            # file:/etcd: sequencer keeps its local durability as an
            # extra floor under the committed ceiling)
            self.seq = RaftSequencer(self.seq, self.election)

    def _raft_unready(self) -> web.Response | None:
        """503 while the Election is still being built (single mode
        binds the port before constructing it; a raft RPC landing in
        that window means a peer is misconfigured, not that we should
        500 with an AttributeError)."""
        if self.election is None:
            return web.json_response(
                {"error": "raft state not loaded yet"}, status=503)
        return None

    async def h_raft_vote(self, req: web.Request) -> web.Response:
        if (err := self._raft_unready()) is not None:
            return err
        body = await req.json()
        lli = body.get("last_log_index")
        r = self.election.on_vote_request(
            int(body["term"]), body["candidate"],
            int(body.get("max_volume_id", 0)),
            last_log_index=None if lli is None else int(lli),
            last_log_term=int(body.get("last_log_term", 0) or 0))
        # raft durability rule: the granted vote / term bump must be
        # on disk before the reply leaves this node (the fsync runs on
        # the executor; a failed write 500s instead of lying)
        await self.election.flush()
        return web.json_response(r)

    async def h_raft_heartbeat(self, req: web.Request) -> web.Response:
        if (err := self._raft_unready()) is not None:
            return err
        body = await req.json()
        if "prev_index" in body:
            r = self.election.on_append(
                int(body["term"]), body["leader"],
                int(body["prev_index"]), int(body["prev_term"]),
                list(body.get("entries", [])), int(body.get("commit", 0)))
        else:
            # legacy pulse (value inline, no log coordinates)
            r = self.election.on_leader_pulse(
                int(body["term"]), body["leader"],
                int(body.get("max_volume_id", 0)))
        await self.election.flush()   # appended entries durable pre-ack
        return web.json_response(r)

    async def h_raft_snapshot(self, req: web.Request) -> web.Response:
        if (err := self._raft_unready()) is not None:
            return err
        body = await req.json()
        r = self.election.on_install_snapshot(
            int(body["term"]), body["leader"], int(body["last_index"]),
            int(body["last_term"]), int(body.get("value", 0)),
            seq=int(body.get("seq", 0)),
            shard_epoch=int(body.get("shard_epoch", 0)),
            shard_map=body.get("shard_map"))
        await self.election.flush()   # term bump / snapshot durable
        return web.json_response(r)

    # ---- filer shard map (filer/shard.py) ----

    def _adopt_shard_map(self, epoch: int, shard_map: dict) -> None:
        """APPLY-time hook: mirror the committed map for serving."""
        if epoch > self.shard_epoch:
            self.shard_epoch = epoch
            self.shard_map = shard_map

    def _shard_map_dict(self) -> dict:
        from ..filer.shard import ShardMap
        if self.shard_map is not None:
            return dict(self.shard_map, epoch=self.shard_epoch)
        return ShardMap(epoch=self.shard_epoch).to_dict()

    async def h_cluster_shards(self, req: web.Request) -> web.Response:
        """GET: the applied shard map (any node serves its own applied
        copy — a stale follower answer only costs the client one
        redirect chase). POST: a map transition, leader-only, raft-
        committed under an epoch CAS so a deposed leader's proposal
        applies as a no-op."""
        if req.method in ("GET", "HEAD"):
            return web.json_response(dict(
                self._shard_map_dict(), leader=self.leader_url or ""))
        if (err := self._raft_unready()) is not None:
            return err
        if not self.is_leader:
            return self._redirect_to_leader(req)
        from ..filer.shard import ShardMap, apply_map_op
        op = await req.json()
        for _ in range(5):
            base = self.shard_epoch
            cur = ShardMap.from_dict(self._shard_map_dict())
            try:
                want = apply_map_op(cur, op)
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            committed = await self.election.append_command(
                {"shard_map": {"base": base, "map": want.to_dict()},
                 "by": self.url})
            if not committed:
                return web.json_response(
                    {"error": "not leader / no quorum"}, status=503)
            # CAS verdict: the op is in iff re-applying it to the NOW
            # applied map is a no-op (every transition is idempotent)
            applied = ShardMap.from_dict(self._shard_map_dict())
            try:
                again = apply_map_op(applied, op)
            except ValueError:
                # e.g. commit_move whose move already completed: the
                # op's effect is behind us either way
                again = applied
            if again.to_dict() == applied.to_dict():
                return web.json_response({"ok": True,
                                          "map": applied.to_dict()})
        return web.json_response(
            {"error": "shard map CAS kept losing"}, status=503)

    async def h_debug_shards(self, req: web.Request) -> web.Response:
        """Merged fleet view: the committed map plus each owner
        filer's local /__debug__/shards (entry counts, move state,
        routing counters). A dead filer degrades its row, not the
        endpoint."""
        m = self._shard_map_dict()
        shards = []
        for sid_s, owner in sorted((m.get("owners") or {}).items(),
                                   key=lambda kv: int(kv[0])):
            row = {"shard": int(sid_s), "url": owner}
            try:
                # chaos site: the fan-out hop is routed traffic too
                await failpoints.fail("filer.shard.route")
                async with self._http.get(
                        tls.url(owner, "/__debug__/shards"),
                        timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    row.update(await resp.json())
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError, AttributeError) as e:
                row["error"] = str(e) or type(e).__name__
            shards.append(row)
        return web.json_response(
            {"epoch": self.shard_epoch, "leader": self.leader_url or "",
             "map": m, "shards": shards})

    # ---- cluster-scope introspection (stats/introspect.py) ----

    def _frame_hub(self):
        if self._introspect_hub is None:
            from ..util.frame import FrameHub
            from ..stats import introspect
            self._introspect_hub = FrameHub(
                ssl=tls.client_ctx(), jwt_key=self.jwt_key,
                request_timeout=introspect.deadline_s())
        return self._introspect_hub

    async def _cluster_fanout(self, req: web.Request, path: str,
                              params: "dict | None", local):
        """One bounded debug pull per known member; any node serves it
        (no leader gate — introspection must work mid-election)."""
        from ..stats import introspect
        nodes = introspect.cluster_nodes(
            self, extra=req.query.get("extra", ""))
        return await introspect.fanout(
            nodes, path, self._http, frame_hub=self._frame_hub(),
            params=params, local=local)

    async def h_cluster_index(self, req: web.Request) -> web.Response:
        """/debug/cluster: the views this master can assemble and the
        member enumeration each one fans out over — the operator's
        entry point (no network pulls: answering must never block)."""
        from ..stats import introspect
        nodes = introspect.cluster_nodes(
            self, extra=req.query.get("extra", ""))
        return web.json_response({
            "views": ["/debug/cluster/trace/<id>",
                      "/debug/cluster/timeline",
                      "/debug/cluster/events",
                      "/debug/cluster/health"],
            "deadline_s": introspect.deadline_s(),
            "nodes": [{"node": nd["node"], "kind": nd["kind"]}
                      for nd in nodes],
        })

    async def h_cluster_trace(self, req: web.Request) -> web.Response:
        """/debug/cluster/trace/<id>: every node's spans for ONE trace
        assembled into a single tree with host/tier attribution and
        explicit missing_nodes rows for members that didn't answer
        inside -introspect.deadline."""
        from ..stats import introspect
        tid = req.match_info["tid"].strip()[:64]
        if not tid:
            return web.json_response({"error": "empty trace id"},
                                     status=400)
        results, missing = await self._cluster_fanout(
            req, "/traces", {"trace": tid},
            local=lambda: tracing.trace_spans_dict(tid))
        return web.json_response(introspect.assemble_trace(
            tid, [(nd["node"], p) for nd, p in results], missing))

    async def h_cluster_timeline(self, req: web.Request) -> web.Response:
        """/debug/cluster/timeline: every member's windows merged with
        the whole-host discipline lifted to cluster scope (sum rates
        and buckets, MAX the non-additive gauges, recompute quantiles
        from merged buckets — never average)."""
        from ..stats import timeline
        try:
            n = tracing.clamp_count(req.query.get("n", 60), cap=10_000)
        except ValueError:
            return web.json_response({"error": "bad n"}, status=400)
        results, missing = await self._cluster_fanout(
            req, "/timeline", {"n": str(n)},
            local=lambda: timeline.timeline_dict(n=n, render=False))
        merged = timeline.merge_payloads([p for _, p in results], n=n)
        merged["nodes"] = len(results)
        merged["missing_nodes"] = missing
        return web.json_response(merged)

    async def h_cluster_events(self, req: web.Request) -> web.Response:
        """/debug/cluster/events: the structured journals of every
        member zipped newest-first, rows tagged with their node."""
        from ..util import events
        try:
            n = tracing.clamp_count(req.query.get("n", 100), cap=10_000)
        except ValueError:
            return web.json_response({"error": "bad n"}, status=400)
        results, missing = await self._cluster_fanout(
            req, "/events", {"n": str(n)},
            local=lambda: events.events_dict(n=n))
        payloads = []
        for nd, p in results:
            p["events"] = [{**r, "node": nd["node"]}
                           for r in p.get("events", ())]
            payloads.append(p)
        merged = events.merge_payloads(payloads, n=n)
        merged["nodes"] = len(results)
        merged["missing_nodes"] = missing
        return web.json_response(merged)

    async def h_cluster_health(self, req: web.Request) -> web.Response:
        """/debug/cluster/health: the SLO verdict evaluated over the
        CLUSTER-merged timeline + journal — burn rates burn on
        cluster-wide buckets, not one host's."""
        from ..stats import slo, timeline
        from ..util import events
        wins = slo.windows_needed()
        (tl_results, tl_missing), (ev_results, _) = await asyncio.gather(
            self._cluster_fanout(
                req, "/timeline", {"n": str(wins)},
                local=lambda: timeline.timeline_dict(n=wins,
                                                     render=False)),
            self._cluster_fanout(
                req, "/events", {"n": "500"},
                local=lambda: events.events_dict(n=500)))
        merged = timeline.merge_payloads([p for _, p in tl_results],
                                         n=wins, render=False)
        evs: list = []
        for _, p in ev_results:
            evs.extend(p.get("events", ()))
        out = slo.health_dict(merged["windows"], events=evs)
        out["nodes"] = len(tl_results)
        out["missing_nodes"] = tl_missing
        return web.json_response(out)

    def _leader_or_503(self) -> tuple[str | None, web.Response | None]:
        """Resolve the current leader, or the 503 every non-leader
        entry point returns while no leader is elected."""
        leader = self.leader_url
        if not leader or leader == self.url:
            return None, web.json_response(
                {"error": "no leader elected yet"}, status=503)
        return leader, None

    def _redirect_to_leader(self, req: web.Request) -> web.Response:
        """Follower answer for every control route: 307 to the leader
        with the ``X-Raft-Leader`` hint (307 preserves method + body,
        so aiohttp/urllib clients land on the leader transparently;
        explicit fleet clients read the hint and re-home). Replaces
        the old whole-body proxy — a follower must not buffer blobs,
        and the whitelist decision belongs on the leader, judged by
        the real client IP."""
        leader, err = self._leader_or_503()
        if err is not None:
            return err
        return web.json_response(
            {"error": "not leader", "leader": leader}, status=307,
            headers={"Location": tls.url(leader, req.path_qs),
                     "X-Raft-Leader": leader})

    async def _proxy_to_leader(self, req: web.Request) -> web.Response:
        """Non-leader HTTP forwards to the leader
        (proxyToLeader, master_server.go:153-185). Only /submit still
        rides this (its multipart body is not reliably replayable
        across a 307 by arbitrary clients); every other control route
        redirects via _redirect_to_leader."""
        leader, err = self._leader_or_503()
        if err is not None:
            return err
        data = await req.read()
        # forward Content-Type: /submit interprets its body by it
        # (multipart vs raw), and dropping it would corrupt the upload
        headers = {"X-Raft-Leader": leader}
        if "Content-Type" in req.headers:
            headers["Content-Type"] = req.headers["Content-Type"]
        try:
            # chaos site: the follower->leader hop is a network hop
            # like any other — error/latency/drop here must surface as
            # a bounded 502 the client's seed rotation absorbs
            await failpoints.fail("master.proxy")
            async with self._http.request(
                    req.method, tls.url(leader, f"{req.path_qs}"),
                    data=data or None, headers=headers) as resp:
                return web.Response(body=await resp.read(),
                                    status=resp.status,
                                    content_type=resp.content_type)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            return web.json_response(
                {"error": f"proxy to leader {leader}: {e}"}, status=502)

    async def _next_fid(self, count: int) -> int:
        """Allocate a fid block under the quorum discipline: ids come
        only from a raft-committed reservation window; when the open
        window cannot cover the block, the leader commits a fresh one
        through the log FIRST (so a successor can never re-issue these
        ids). Raises SequenceUnavailable when no window can be
        committed — the caller errors/redirects, exactly the deposed
        mid-assign contract."""
        for _ in range(3):
            try:
                return self.seq.next_file_id(count)
            except SequenceBehind:
                if not isinstance(self.seq, RaftSequencer) \
                        or not await self.seq.reserve(count):
                    raise SequenceUnavailable(
                        "no committed fid window (not leader?)") \
                        from None
        raise SequenceUnavailable("fid window kept burning under "
                                  "racing heartbeat watermarks")

    # ---- handlers ----

    async def h_health(self, req: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    async def h_metrics(self, req: web.Request) -> web.Response:
        from ..stats.metrics import metrics_text
        return web.Response(body=metrics_text(),
                            content_type="text/plain")

    async def h_heartbeat(self, req: web.Request) -> web.Response:
        if not self.is_leader:
            # volume servers must register with the leader. 307 lands
            # this very pulse on the leader (aiohttp re-sends the JSON
            # body), so a re-homing fleet loses ZERO pulses; the body
            # keeps the legacy rejected+hint shape for clients that
            # don't follow redirects (master_grpc_server.go:165-175)
            leader = self.leader_url
            if leader and leader != self.url:
                return web.json_response(
                    {"rejected": True, "leader": leader}, status=307,
                    headers={"Location": tls.url(leader, req.path_qs),
                             "X-Raft-Leader": leader})
            return web.json_response({"rejected": True, "leader": ""})
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.MASTER_RECEIVED_HEARTBEATS.inc()
        try:
            raw = await req.json()
            hb = pb.Heartbeat.from_dict(raw)
        except (ValueError, TypeError, KeyError, AttributeError):
            return web.json_response({"error": "bad heartbeat body"},
                                     status=400)
        if not hb.ip or not hb.port:
            return web.json_response(
                {"error": "heartbeat without ip:port"}, status=400)
        # auto-admit the sender as a cluster peer ONLY now that the body
        # parsed as a real volume-server registration on the leader path
        # — an empty POST must not whitelist-bypass /dir/lookup. Residual
        # exposure: a client that forges a full valid heartbeat is still
        # admitted (and registered); the mesh trust boundary without
        # security.toml mTLS is the heartbeat body, as in the reference.
        remote = self._remote(req)
        if remote:
            self._peer_ips.add(remote)
        node = self.topo.register_heartbeat(hb)
        self.seq.set_max(hb.max_file_key)
        self._refresh_writable(node)
        # publish location deltas to watchers (KeepConnected analog)
        if hb.new_volumes or hb.deleted_volumes or hb.new_ec_shards \
                or hb.deleted_ec_shards:
            self._publish({
                "url": node.url, "public_url": node.public_url,
                "new_vids": sorted({m.id for m in hb.new_volumes}
                                   | {m.id for m in hb.new_ec_shards}),
                "deleted_vids": sorted({m.id for m in hb.deleted_volumes}
                                       | {m.id for m in hb.deleted_ec_shards}),
            })
        out = {
            "volume_size_limit": self.volume_size_limit,
            "leader": self.url,
        }
        # cluster-wide bandwidth arbitration rides the pulse: the node
        # reports its foreground byte rate, the leader publishes the
        # -qos.mbps budget every arbiter in the fleet paces against
        arb = qos.arbiter()
        if arb is not None:
            fg = raw.get("qos_fg_bps")
            if isinstance(fg, (int, float)):
                arb.note_node_foreground(node.url, float(fg))
            if arb.budget_bps > 0:
                out["qos_mbps"] = round(arb.budget_bps / (1 << 20), 3)
        return web.json_response(out)

    async def h_seq_lease(self, req: web.Request) -> web.Response:
        """Lease a block of file ids to an assign accelerator
        (server/workers.py): the accelerator hands them out without a
        round trip per assign. Ids in an abandoned lease are simply
        never used — file keys are sparse by design."""
        if not self._worker_auth(req):
            return web.json_response({"error": "forbidden"}, status=403)
        if not self.is_leader:
            return web.json_response(
                {"error": "not leader", "leader": self.leader_url or ""},
                status=503)
        try:
            count = max(1, min(int(req.query.get("count", 1024)),
                               1 << 20))
        except ValueError:
            return web.json_response({"error": "bad count"}, status=400)
        try:
            start = await self._next_fid(count)
        except SequenceUnavailable:
            return web.json_response(
                {"error": "not leader", "leader": self.leader_url or ""},
                status=503)
        return web.json_response({"start": start, "count": count})

    async def h_assign_state(self, req: web.Request) -> web.Response:
        """Writable-volume snapshot for one layout key — everything an
        accelerator needs to answer /dir/assign locally: vids with
        enough live replicas plus their primary location."""
        if not self._worker_auth(req):
            return web.json_response({"error": "forbidden"}, status=403)
        if not self.is_leader:
            return web.json_response({"entries": [],
                                      "leader": self.leader_url or ""})
        q = req.query
        collection = q.get("collection", "")
        replication = q.get("replication", "") or self.default_replication
        ttl = q.get("ttl", "")
        try:
            rp = ReplicaPlacement.parse(replication)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        lay = self._layout(collection, replication, ttl)
        entries = []
        for vid in sorted(lay.writable):
            nodes = self.topo.lookup(vid)
            if len(nodes) >= rp.copy_count:
                entries.append({"vid": vid, "url": nodes[0].url,
                                "publicUrl": nodes[0].public_url})
        return web.json_response({"entries": entries})

    async def h_assign(self, req: web.Request) -> web.Response:
        if not self.is_leader:
            return self._redirect_to_leader(req)
        try:
            # chaos site: injected assign faults (error => client retry
            # with backoff; latency => client deadline discipline)
            await failpoints.fail("master.assign")
        except OSError as e:
            return web.json_response({"error": str(e)}, status=503)
        q = req.query
        count = int(q.get("count", 1) or 1)
        collection = q.get("collection", "")
        replication = q.get("replication", "") or self.default_replication
        ttl = q.get("ttl", "")
        data_center = q.get("dataCenter", "")
        try:
            rp = ReplicaPlacement.parse(replication)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)

        lay = self._layout(collection, replication, ttl)
        vid = lay.pick_for_write(self.topo, rp.copy_count)
        if vid is None:
            # serialize growth: concurrent assigns must not each grow a
            # volume and overshoot node capacity (vgChan in the reference)
            async with self._grow_lock:
                vid = lay.pick_for_write(self.topo, rp.copy_count)
                if vid is None:
                    try:
                        await self._grow(lay, rp, collection, replication,
                                         ttl, data_center)
                    except PlacementError as e:
                        return web.json_response({"error": str(e)},
                                                 status=500)
                    vid = lay.pick_for_write(self.topo, rp.copy_count)
            if vid is None:
                return web.json_response(
                    {"error": "no writable volumes after growth"}, status=500)
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.MASTER_ASSIGN_REQUESTS.labels("ok").inc()
        try:
            key = await self._next_fid(count)
        except SequenceUnavailable:
            # deposed mid-assign: the in-flight request errors or
            # redirects — it NEVER gets a fid outside a committed
            # reservation window (tools/chaos.py ha's core invariant)
            if not self.is_leader:
                return self._redirect_to_leader(req)
            return web.json_response(
                {"error": "fid reservation lost quorum",
                 "leader": self.leader_url or ""}, status=503)
        fid = str(t.FileId(vid, key, t.random_cookie()))
        nodes = self.topo.lookup(vid)
        node = nodes[0]
        out = {
            "fid": fid, "url": node.url, "publicUrl": node.public_url,
            "count": count,
        }
        if self.jwt_key:
            from ..security.jwt import gen_jwt
            out["auth"] = gen_jwt(self.jwt_key, fid)
        return web.json_response(out)

    async def _grow(self, lay: VolumeLayout, rp: ReplicaPlacement,
                    collection: str, replication: str, ttl: str,
                    data_center: str) -> None:
        """AutomaticGrowByType: place + AllocateVolume on each target
        (volume_growth.go:204-230, allocate_volume.go)."""
        nodes = find_empty_slots(self.topo, rp, data_center or None)
        vid = self.topo.next_volume_id()
        if self.election and not await self.election.commit_max_volume_id():
            # the new id must reach a majority before any volume exists
            # under it, or a successor leader could reissue it
            raise PlacementError(
                f"vid {vid}: MaxVolumeId not replicated to a quorum")
        prealloc = str(self.volume_size_limit
                       if self.volume_preallocate else 0)
        # chaos site: the allocate fan-out to volume servers — an
        # injected fault is a failed growth (PlacementError), never a
        # half-registered volume the layout would hand out
        try:
            await failpoints.fail("master.grow")
        except OSError as e:
            raise PlacementError(f"injected grow fault: {e}") from e
        for n in nodes:
            async with self._http.post(
                    tls.url(n.url, "/admin/volume/allocate"),
                    params={"volume": str(vid), "collection": collection,
                            "replication": replication, "ttl": ttl,
                            "preallocate": prealloc}) as resp:
                if resp.status != 200:
                    raise PlacementError(
                        f"allocate vid {vid} on {n.url}: "
                        f"{await resp.text()}")
            m = pb.VolumeInformationMessage(
                id=vid, collection=collection,
                replica_placement=rp.to_byte(),
                ttl=t.TTL.parse(ttl).to_uint32())
            n.volumes[m.id] = m
            self.topo.register_volume(m, n)
        lay.set_writable(vid, True)

    async def h_lookup(self, req: web.Request) -> web.Response:
        if not self.is_leader:
            return self._redirect_to_leader(req)
        q = req.query
        vid_s = q.get("volumeId", "") or q.get("fileId", "")
        if "," in vid_s:
            vid_s = vid_s.split(",")[0]
        try:
            vid = int(vid_s)
        except ValueError:
            return web.json_response(
                {"error": f"unknown volumeId {vid_s!r}"}, status=400)
        nodes = self.topo.lookup(vid)
        if not nodes:
            return web.json_response(
                {"volumeId": vid_s, "error": "volume id not found"},
                status=404)
        return web.json_response({
            "volumeId": vid_s,
            "locations": [{"url": n.url, "publicUrl": n.public_url}
                          for n in nodes],
        })

    async def h_vacuum(self, req: web.Request) -> web.Response:
        """HTTP vacuum trigger (master_server.go:116 volumeVacuumHandler):
        the manual form of the auto-vacuum loop, same underlying
        check -> compact -> commit workflow."""
        if not self.is_leader:
            return self._redirect_to_leader(req)
        from ..shell import volume_commands as vc
        from ..shell.env import CommandEnv
        try:
            threshold = float(req.query.get("garbageThreshold",
                                            self.garbage_threshold))
        except ValueError:
            return web.json_response(
                {"error": "bad garbageThreshold"}, status=400)
        async with CommandEnv(self.url, session=self._http) as env:
            res = await vc.volume_vacuum(env, threshold)
        return web.json_response({"vacuumed": res})

    async def h_submit(self, req: web.Request) -> web.Response:
        """One-shot upload through the master: assign + store
        (master_server_handlers.go:117 submitFromMasterServerHandler,
        operation.SubmitFiles)."""
        if not self.is_leader:
            return await self._proxy_to_leader(req)
        from ..util.client import OperationError, WeedClient
        name = ""
        mime = ""
        ctype = req.headers.get("Content-Type", "")
        data = b""
        if ctype.startswith("multipart/form-data"):
            mp = await req.multipart()
            async for part in mp:
                if part.filename or part.name in ("file", None):
                    name = part.filename or ""
                    pct = part.headers.get("Content-Type", "")
                    if pct and pct != "application/octet-stream":
                        mime = pct
                    data = await part.read()
                    break
        else:
            data = await req.read()
            if ctype and ctype != "application/octet-stream":
                mime = ctype.split(";")[0]
        if not data:
            return web.json_response({"error": "no file content"},
                                     status=400)
        q = req.query
        try:
            async with WeedClient(self.url, session=self._http,
                                  jwt_key=self.jwt_key) as c:
                a = await c.assign(collection=q.get("collection", ""),
                                   replication=q.get("replication", ""),
                                   ttl=q.get("ttl", ""))
                if "fid" not in a:
                    return web.json_response(a, status=500)
                await c.upload(a["fid"], a["url"], data, mime=mime,
                               ttl=q.get("ttl", ""), auth=a.get("auth", ""))
        except (OperationError, aiohttp.ClientError,
                asyncio.TimeoutError, OSError) as e:
            # keep the JSON error contract even for connection-level
            # failures between assign and upload
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({
            "fid": a["fid"],
            "fileUrl": f"{a.get('publicUrl') or a['url']}/{a['fid']}",
            "fileName": name, "size": len(data)})

    async def h_fid_redirect(self, req: web.Request) -> web.Response:
        """GET /<fid>: redirect to a volume server holding the volume
        (master_server.go:121 redirectHandler)."""
        if not self.is_leader:
            # topology is heartbeat-fed on the leader only; bounce the
            # CLIENT there (proxying would buffer whole blobs in this
            # process and swallow the leader's redirect)
            leader, err = self._leader_or_503()
            if err is not None:
                return err
            raise web.HTTPFound(
                location=tls.url(leader, f"/{req.match_info['fid']}"),
                headers={"X-Raft-Leader": leader})
        fid = req.match_info["fid"]
        vid_s = fid.split(",")[0]
        try:
            vid = int(vid_s)
        except ValueError:
            return web.json_response({"error": f"bad fileId {fid!r}"},
                                     status=404)
        nodes = self.topo.lookup(vid)
        if not nodes:
            return web.json_response(
                {"error": f"volume {vid} not found"}, status=404)
        loc = nodes[hash(fid) % len(nodes)]
        raise web.HTTPMovedPermanently(
            location=tls.url(loc.public_url or loc.url, f"/{fid}"))

    async def h_dir_status(self, req: web.Request) -> web.Response:
        dcs = []
        for dc in self.topo.data_centers.values():
            racks = []
            for r in dc.racks.values():
                racks.append({
                    "id": r.id,
                    "nodes": [{
                        "id": n.id, "url": n.url, "publicUrl": n.public_url,
                        "volumes": len(n.volumes),
                        "ecShards": n.ec_shard_count(),
                        "max": n.max_volume_count,
                    } for n in r.nodes.values()],
                })
            dcs.append({"id": dc.id, "racks": racks})
        return web.json_response({
            "topology": {"datacenters": dcs,
                         "max_volume_id": self.topo.max_volume_id},
            "version": "seaweedfs_tpu 0.1",
        })

    async def h_volumes(self, req: web.Request) -> web.Response:
        """VolumeList analog: every volume + EC shard set with locations."""
        if not self.is_leader:
            return self._redirect_to_leader(req)
        out = []
        for node in self.topo.all_nodes():
            out.append({
                "url": node.url, "publicUrl": node.public_url,
                "dataCenter": node.rack.data_center.id if node.rack else "",
                "rack": node.rack.id if node.rack else "",
                "maxVolumes": node.max_volume_count,
                "freeSlots": node.free_space(),
                "volumes": [m.to_dict() for m in node.volumes.values()],
                "ecShards": [m.to_dict() for m in node.ec_shards.values()],
            })
        return web.json_response({
            "nodes": out,
            "volumeSizeLimitMB": self.volume_size_limit >> 20})

    async def h_ec_lookup(self, req: web.Request) -> web.Response:
        """vid -> {shard_id: [urls]} (LookupEcVolume, topology_ec.go:97-133)."""
        if not self.is_leader:
            return self._redirect_to_leader(req)
        vid = int(req.query["volumeId"])
        by_shard = self.topo.ec_shard_locations.get(vid)
        if not by_shard:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({
            "volumeId": vid,
            "shards": {str(sid): [n.url for n in nodes]
                       for sid, nodes in by_shard.items()},
        })

    async def h_cluster_status(self, req: web.Request) -> web.Response:
        return web.json_response({
            "isLeader": self.is_leader,
            "leader": self.leader_url or "",
            "term": self.election.term if self.election else 0,
            "peers": self._peers})

    async def h_grow(self, req: web.Request) -> web.Response:
        if not self.is_leader:
            return self._redirect_to_leader(req)
        q = req.query
        collection = q.get("collection", "")
        replication = q.get("replication", "") or self.default_replication
        ttl = q.get("ttl", "")
        count = int(q.get("count", 1) or 1)
        rp = ReplicaPlacement.parse(replication)
        lay = self._layout(collection, replication, ttl)
        grown = 0
        for _ in range(count):
            try:
                await self._grow(lay, rp, collection, replication, ttl,
                                 q.get("dataCenter", ""))
                grown += 1
            except PlacementError as e:
                return web.json_response(
                    {"error": str(e), "count": grown}, status=500)
        return web.json_response({"count": grown})

    async def h_collection_delete(self, req: web.Request) -> web.Response:
        if not self.is_leader:
            return self._redirect_to_leader(req)
        collection = req.query.get("collection", "")
        deleted = []
        for node in self.topo.all_nodes():
            vids = [m.id for m in node.volumes.values()
                    if m.collection == collection]
            for vid in vids:
                # chaos site: per-holder delete dispatch — a failed hop
                # surfaces as a bounded 503 with the partial result
                try:
                    await failpoints.fail("master.col_delete")
                    async with self._http.post(
                            tls.url(node.url, "/admin/volume/delete"),
                            params={"volume": str(vid)}) as resp:
                        await resp.read()
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as e:
                    return web.json_response(
                        {"error": f"delete vid {vid} on {node.url}: {e}",
                         "deleted": sorted(set(deleted))}, status=503)
                deleted.append(vid)
        return web.json_response({"deleted": sorted(set(deleted))})

    async def h_ui(self, req: web.Request) -> web.Response:
        """Live cluster status page (server/master_ui/templates.go)."""
        from html import escape
        rows = []
        for node in self.topo.all_nodes():
            # heartbeat-supplied strings are untrusted: escape everything
            dc = escape(node.rack.data_center.id if node.rack else "")
            rack = escape(node.rack.id if node.rack else "")
            url = escape(node.url)
            # under mesh mTLS a browser can't present the role client
            # cert, so don't render a link it cannot follow
            cell = (url if tls.enabled() else
                    f"<a href='{escape(tls.url(node.url, '/ui'), quote=True)}'>"
                    f"{url}</a>")
            rows.append(
                f"<tr><td>{dc}</td><td>{rack}</td>"
                f"<td>{cell}</td><td>{len(node.volumes)}</td>"
                f"<td>{node.ec_shard_count()}</td>"
                f"<td>{node.max_volume_count}</td></tr>")
        html = f"""<!DOCTYPE html><html><head><title>seaweedfs_tpu master
</title></head><body><h1>seaweedfs_tpu master {self.url}</h1>
<p>leader: {self.leader_url or '(none)'} | term:
{self.election.term if self.election else 0} | max volume id:
{self.topo.max_volume_id} | sequencer at: {self.seq.peek()}</p>
<h2>Topology</h2>
<table border=1 cellpadding=4><tr><th>DC</th><th>Rack</th><th>Node</th>
<th>Volumes</th><th>EC shards</th><th>Max</th></tr>{''.join(rows)}</table>
</body></html>"""
        return web.Response(text=html, content_type="text/html")

    # ---- watch stream (KeepConnected pubsub, master_grpc_server.go:181) ----

    def _publish(self, update: dict) -> None:
        for q in self._watchers:
            q.put_nowait(update)

    async def h_watch(self, req: web.Request) -> web.StreamResponse:
        if not self.is_leader:
            # a follower has no topology; hand the subscriber the leader
            # hint (wdclient reconnects there, masterclient.py:158-162)
            resp = web.StreamResponse(
                headers={"Content-Type": "application/x-ndjson"})
            await resp.prepare(req)
            await resp.write(json.dumps(
                {"leader": self.leader_url or ""}).encode() + b"\n")
            await resp.write_eof()
            return resp
        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"})
        await resp.prepare(req)
        # register BEFORE writing the snapshot: each write awaits, and a
        # delta published mid-snapshot would otherwise be lost to this
        # subscriber forever (apply is idempotent, so the duplicate a
        # racing delta can cause is harmless)
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)
        try:
            # initial full state (snapshot: heartbeats mutate these dicts)
            for vid, locs in list(self.topo.volume_locations.items()):
                for n in list(locs.values()):
                    await resp.write(json.dumps({
                        "url": n.url, "public_url": n.public_url,
                        "new_vids": [vid],
                        "deleted_vids": []}).encode() + b"\n")
            # explicit end-of-snapshot marker so subscribers know when
            # their map is complete (KeepConnected's initial sync boundary)
            await resp.write(b'{"synced": true}\n')
            while True:
                if not self.is_leader:
                    # deposed mid-stream: this master no longer receives
                    # heartbeats, so the subscriber's map would silently
                    # go stale; redirect it to the new leader
                    await resp.write(json.dumps(
                        {"leader": self.leader_url or ""}).encode() + b"\n")
                    break
                try:
                    update = await asyncio.wait_for(q.get(), timeout=1.0)
                except asyncio.TimeoutError:
                    # keepalive doubles as disconnect detection, so dead
                    # subscribers don't pin the handler (and shutdown
                    # isn't held hostage by the blocking q.get())
                    await resp.write(b"\n")
                    continue
                await resp.write(json.dumps(update).encode() + b"\n")
        except (asyncio.CancelledError, ConnectionError):
            pass
        finally:
            self._watchers.remove(q)
        return resp

    # ---- automatic maintenance (leader-only) ----

    async def h_autopilot(self, req: web.Request) -> web.Response:
        """/debug/autopilot: maintenance-plane status (plan queue,
        in-flight actions, per-cycle ledgers incl. dry-run). POST
        ?run=1 forces one observe -> plan -> execute cycle NOW and
        returns its report — how tests and the heal soak drive
        deterministic convergence. Leader-only for POST: a follower
        has no topology to observe."""
        if req.method == "POST":
            if req.query.get("run", "") not in ("1", "true"):
                return web.json_response(
                    {"error": "POST wants ?run=1"}, status=400)
            if not self.is_leader:
                return web.json_response(
                    {"error": "not leader",
                     "leader": self.leader_url or ""}, status=503)
            report = await self.autopilot.run_cycle()
            return web.json_response({
                "cycle": report, "status": self.autopilot.status()})
        if req.method != "GET":
            return web.json_response({"error": "method not allowed"},
                                     status=405)
        return web.json_response({"autopilot": self.autopilot.status()})

    async def _auto_vacuum_loop(self) -> None:
        """Vacuum volumes whose garbage ratio exceeds the threshold, with
        no shell interaction (topology_event_handling.go:22-28; the
        reference's topo.Vacuum timer)."""
        from ..shell import volume_commands as vc
        from ..shell.env import CommandEnv
        while True:
            await asyncio.sleep(self.maintenance_interval_s)
            if not self.is_leader:
                continue
            try:
                async with CommandEnv(self.url,
                                      session=self._http) as env:
                    res = await vc.volume_vacuum(env,
                                                 self.garbage_threshold)
                if res:
                    glog.info("auto-vacuum: %s", res)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — upkeep must not die
                glog.warning("auto-vacuum failed: %s", e)

    async def _admin_scripts_loop(self) -> None:
        """Run the configured admin script lines (master.toml
        [master.maintenance] scripts) every sleep interval
        (master_server.go:186-250 startAdminScripts)."""
        from ..shell.env import CommandEnv
        from ..shell.runner import dispatch
        while True:
            await asyncio.sleep(self.admin_scripts_interval_s)
            if not self.is_leader:
                continue
            for line in self.admin_scripts:
                try:
                    async with CommandEnv(self.url,
                                          session=self._http) as env:
                        res = await dispatch(env, line)
                    glog.V(1).infof("admin script %r: %s", line, res)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    glog.warning("admin script %r failed: %s", line, e)

    # ---- liveness sweep (topology_event_handling.go:13-21) ----

    async def _liveness_loop(self) -> None:
        while True:
            await asyncio.sleep(self.topo.pulse_seconds)
            for node in self.topo.dead_nodes():
                vids = self.topo.unregister_node(node)
                for lay in self.layouts.values():
                    for vid in vids:
                        # volumes that lost replicas below quorum stop
                        # being writable until re-registered
                        if vid in lay.writable and not self.topo.lookup(vid):
                            lay.set_writable(vid, False)
                self._publish({"url": node.url,
                               "public_url": node.public_url,
                               "new_vids": [],
                               "deleted_vids": sorted(set(vids))})
