"""models subpackage."""
