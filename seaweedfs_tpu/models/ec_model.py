"""Flagship jittable pipelines ("models") of the framework.

The compute heart of the system is the RS(10,4) GF(256) shard transform;
these are its end-to-end jittable forms, the analog of a model-forward /
train-step in an ML framework:

  encode_step   — forward: 10 data shards -> 14 shards (parity matmul)
  rebuild_step  — recovery: any 10 shard rows -> requested lost rows
  verify_step   — recompute parity and reduce a mismatch count

Reference equivalents: reedsolomon Encode/Reconstruct at ec_encoder.go:192,
264 and store_ec.go:322.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ec import gf
from ..ec.encoder_jax import _apply_bitplanes


def make_encode_step(use_pallas: bool | None = None):
    """Returns fn(data (..., 10, n) uint8) -> (..., 14, n) uint8, jittable."""
    consts = gf.bitplane_constants(gf.parity_matrix())
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    if use_pallas:
        from ..ops.gf256_pallas import gf256_matmul_pallas

        def step(data):
            parity = gf256_matmul_pallas(consts, data)
            return jnp.concatenate([jnp.asarray(data, jnp.uint8), parity],
                                   axis=-2)
    else:
        def step(data):
            data = jnp.asarray(data, jnp.uint8)
            parity = _apply_bitplanes(consts, data)
            return jnp.concatenate([data, parity], axis=-2)
    return step


def make_rebuild_step(present_rows: list[int], want_rows: list[int],
                      use_pallas: bool | None = None):
    """Returns fn(shards (..., 10, n)) -> (..., len(want), n), jittable."""
    coeff = gf.shard_rows(list(want_rows), list(present_rows))
    consts = gf.bitplane_constants(coeff)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    if use_pallas:
        from ..ops.gf256_pallas import gf256_matmul_pallas

        def step(shards):
            return gf256_matmul_pallas(consts, shards)
    else:
        def step(shards):
            return _apply_bitplanes(consts, jnp.asarray(shards, jnp.uint8))
    return step


def make_verify_step(use_pallas: bool | None = None):
    """Returns fn(shards (..., 14, n)) -> scalar int32 mismatch count."""
    consts = gf.bitplane_constants(gf.parity_matrix())
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    def step(shards):
        shards = jnp.asarray(shards, jnp.uint8)
        data, parity = shards[..., :gf.DATA_SHARDS, :], \
            shards[..., gf.DATA_SHARDS:, :]
        if use_pallas:
            from ..ops.gf256_pallas import gf256_matmul_pallas
            want = gf256_matmul_pallas(consts, data)
        else:
            want = _apply_bitplanes(consts, data)
        return jnp.sum((want != parity).astype(jnp.int32))
    return step


def example_inputs(batch: int = 0, n: int = 64 * 1024,
                   seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    shape = (batch, gf.DATA_SHARDS, n) if batch else (gf.DATA_SHARDS, n)
    return rng.integers(0, 256, shape).astype(np.uint8)
