"""FUSE-style mount layer: filer-backed VFS nodes with write-back caching.

Reference: weed/filesys/ (wfs.go, dir.go, file.go, filehandle.go,
dirty_page.go, xattr.go, wfs_deletion.go — 1,631 LoC). The node layer here
is kernel-agnostic: ops are plain async methods so the full semantics are
testable in-proc; `fuse_adapter` bridges to a real kernel mount when a
FUSE binding is importable.
"""

from .wfs import WFS, MountOptions  # noqa: F401
