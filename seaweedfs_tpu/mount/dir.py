"""Directory node ops.

Reference: weed/filesys/dir.go:1-426 (Lookup/Create/Mkdir/ReadDirAll/
Remove/Setattr + xattr), dir_rename.go (rename via filer atomic rename).
"""

from __future__ import annotations

import time

from ..filer.entry import Attr, Entry, new_directory_entry
from ..filer.filer import FilerError


class MountError(Exception):
    def __init__(self, errno_name: str, msg: str = ""):
        self.errno_name = errno_name  # ENOENT / EEXIST / ENOTEMPTY / ...
        super().__init__(f"{errno_name}: {msg}")


class Dir:
    def __init__(self, path: str, wfs):
        self.path = path
        self.wfs = wfs

    def _child_path(self, name: str) -> str:
        return f"{self.path.rstrip('/')}/{name}"

    # ---- lookup / attr ----

    async def lookup(self, name: str):
        """dir.go Lookup (:194-235): resolve a child to a Dir or File
        node, with the entry cache standing in for fuse attr Valid."""
        from .file import File

        path = self._child_path(name)
        entry = self.wfs.cache_get(path)
        if entry is None:
            entry = self.wfs.filer.find_entry(path)
            if entry is not None:
                self.wfs.cache_set(path, entry)
        if entry is None:
            raise MountError("ENOENT", path)
        if entry.is_directory:
            return Dir(path, self.wfs)
        return File(name, self, entry=entry)

    async def attr(self) -> Attr:
        if self.path == "/":
            return Attr(mode=0o40777)
        entry = self.wfs.filer.find_entry(self.path)
        if entry is None:
            raise MountError("ENOENT", self.path)
        return entry.attr

    # ---- create / mkdir ----

    async def create(self, name: str, mode: int = 0o660,
                     uid: int = 0, gid: int = 0):
        """dir.go Create (:93-134): insert an empty entry, return the
        File node and an open FileHandle."""
        from .file import File

        path = self._child_path(name)
        now = time.time()
        entry = Entry(full_path=path, attr=Attr(
            mtime=now, crtime=now, mode=mode & 0o7777, uid=uid, gid=gid,
            collection=self.wfs.option.collection,
            replication=self.wfs.option.replication))
        self.wfs.filer.create_entry(entry)
        self.wfs.cache_set(path, entry)
        f = File(name, self, entry=entry)
        return f, f.open(uid=uid, gid=gid)

    async def mkdir(self, name: str, mode: int = 0o770) -> "Dir":
        path = self._child_path(name)
        if self.wfs.filer.find_entry(path) is not None:
            raise MountError("EEXIST", path)
        self.wfs.filer.create_entry(
            new_directory_entry(path, mode & 0o7777))
        return Dir(path, self.wfs)

    # ---- readdir ----

    async def read_dir_all(self) -> list[Entry]:
        """dir.go ReadDirAll (:237-258), paginated like the reference's
        1024-entry filer pages."""
        out: list[Entry] = []
        start = ""
        while True:
            page = self.wfs.filer.list_directory_entries(
                self.path, start_file=start, inclusive=False, limit=1024)
            out.extend(page)
            if len(page) < 1024:
                return out
            start = page[-1].name

    # ---- remove / rename ----

    async def remove(self, name: str, is_dir: bool = False) -> None:
        """dir.go Remove (:260-303): file removal deletes data chunks
        too; directory removal requires empty (rmdir semantics)."""
        path = self._child_path(name)
        entry = self.wfs.filer.find_entry(path)
        if entry is None:
            raise MountError("ENOENT", path)
        if is_dir != entry.is_directory:
            raise MountError("ENOTDIR" if is_dir else "EISDIR", path)
        try:
            self.wfs.filer.delete_entry(path, recursive=False)
        except FilerError as e:
            if "not empty" in str(e):
                raise MountError("ENOTEMPTY", path) from e
            raise
        self.wfs.cache_invalidate(path)

    async def rename(self, old_name: str, new_dir: "Dir",
                     new_name: str) -> None:
        """dir_rename.go: delegates to the filer's atomic rename."""
        old_path = self._child_path(old_name)
        new_path = new_dir._child_path(new_name)
        try:
            self.wfs.filer.rename_entry(old_path, new_path)
        except FilerError as e:
            raise MountError("ENOENT", str(e)) from e
        self.wfs.cache_invalidate(old_path)
        self.wfs.cache_invalidate(new_path)

    # ---- setattr / xattr (dir.go:305-358, xattr.go) ----

    async def setattr(self, mode: int | None = None,
                      uid: int | None = None,
                      gid: int | None = None,
                      mtime: float | None = None) -> None:
        entry = self.wfs.filer.find_entry(self.path)
        if entry is None:
            raise MountError("ENOENT", self.path)
        if mode is not None:
            entry.attr.mode = (entry.attr.mode & ~0o7777) | (mode & 0o7777)
        if uid is not None:
            entry.attr.uid = uid
        if gid is not None:
            entry.attr.gid = gid
        if mtime is not None:
            entry.attr.mtime = mtime
        self.wfs.filer.update_entry(None, entry)
        self.wfs.cache_invalidate(self.path)

    async def get_xattr(self, name: str) -> bytes:
        return await _get_xattr(self.wfs, self.path, name)

    async def set_xattr(self, name: str, value: bytes) -> None:
        await _set_xattr(self.wfs, self.path, name, value)

    async def list_xattr(self) -> list[str]:
        return await _list_xattr(self.wfs, self.path)

    async def remove_xattr(self, name: str) -> None:
        await _remove_xattr(self.wfs, self.path, name)


# ---- shared xattr helpers (xattr.go:15-144; stored in Entry.extended) ----

async def _entry_of(wfs, path: str) -> Entry:
    entry = wfs.filer.find_entry(path)
    if entry is None:
        raise MountError("ENOENT", path)
    return entry


async def _get_xattr(wfs, path: str, name: str) -> bytes:
    entry = await _entry_of(wfs, path)
    if name not in entry.extended:
        raise MountError("ENODATA", name)
    return bytes.fromhex(entry.extended[name])


async def _set_xattr(wfs, path: str, name: str, value: bytes) -> None:
    entry = await _entry_of(wfs, path)
    entry.extended[name] = value.hex()
    wfs.filer.update_entry(None, entry)
    wfs.cache_invalidate(path)


async def _list_xattr(wfs, path: str) -> list[str]:
    entry = await _entry_of(wfs, path)
    return sorted(entry.extended)


async def _remove_xattr(wfs, path: str, name: str) -> None:
    entry = await _entry_of(wfs, path)
    if name not in entry.extended:
        raise MountError("ENODATA", name)
    del entry.extended[name]
    wfs.filer.update_entry(None, entry)
    wfs.cache_invalidate(path)
