"""Write-back cache of one contiguous dirty byte range per open file.

Reference: weed/filesys/dirty_page.go:17-220. Writes accumulate in a
single contiguous buffer; a write that is non-contiguous, overflows the
buffer, or exceeds the chunk size limit forces a flush (assign fid +
upload to a volume server), yielding FileChunks that overlay earlier ones
by mtime.
"""

from __future__ import annotations

import time

from ..filer.filechunks import FileChunk


class ContinuousDirtyPages:
    def __init__(self, file) -> None:
        self.file = file
        self.offset = 0
        self.size = 0
        self.data: bytearray | None = None

    @property
    def _limit(self) -> int:
        return self.file.wfs.option.chunk_size_limit

    async def add_page(self, offset: int, data: bytes) -> list[FileChunk]:
        """Buffer [offset, offset+len) and return any chunks flushed to
        make room (dirty_page.go:44-120)."""
        if len(data) > self._limit:
            # larger than the buffer can ever hold: flush what we have,
            # then save the oversized write directly, split into
            # chunk-size pieces (dirty_page.go flushAndSave :122-160)
            return await self._flush_and_save(offset, data)

        chunks: list[FileChunk] = []
        if self.data is None:
            self.data = bytearray(self._limit)

        out_of_range = (
            offset < self.offset
            or offset >= self.offset + self._limit
            or self.offset + self._limit < offset + len(data))
        if out_of_range:
            # out of the buffer window: flush and restart the window here
            # (dirty_page.go:62-83)
            saved = await self._save_existing()
            if saved is not None:
                chunks.append(saved)
            self.offset = offset
            self.data[:len(data)] = data
            self.size = len(data)
            return chunks

        if self.size == 0:
            # empty buffer (fresh handle, or just flushed): restart the
            # window wherever this write lands
            self.offset = offset
            self.data[:len(data)] = data
            self.size = len(data)
            return chunks

        if offset != self.offset + self.size:
            if offset == self.offset and self.size < len(data):
                # re-write from the start that extends the buffered range
                # (dirty_page.go:87-91)
                self.data[:len(data)] = data
                self.size = len(data)
                return chunks
            # non-append write inside the window: the buffer only holds
            # one contiguous run, so flush it and save this write as its
            # own chunk (dirty_page.go:92-97)
            return await self._flush_and_save(offset, data)

        start = offset - self.offset
        self.data[start:start + len(data)] = data
        self.size = start + len(data)
        return chunks

    async def _flush_and_save(self, offset: int,
                              data: bytes) -> list[FileChunk]:
        chunks: list[FileChunk] = []
        saved = await self._save_existing()
        if saved is not None:
            chunks.append(saved)
        for i in range(0, len(data), self._limit):
            piece = data[i:i + self._limit]
            chunks.append(await self._save_to_storage(offset + i, piece))
        return chunks

    async def flush(self) -> FileChunk | None:
        """Save any remaining buffered range (saveExistingPagesToStorage,
        dirty_page.go:162-177)."""
        return await self._save_existing()

    async def _save_existing(self) -> FileChunk | None:
        if self.size == 0 or self.data is None:
            return None
        chunk = await self._save_to_storage(
            self.offset, bytes(self.data[:self.size]))
        self.size = 0
        return chunk

    async def _save_to_storage(self, offset: int,
                               data: bytes) -> FileChunk:
        """assign + upload one chunk (dirty_page.go:179-210)."""
        wfs = self.file.wfs
        fid, etag = await wfs.save_data_as_chunk(data)
        return FileChunk(file_id=fid, offset=offset, size=len(data),
                         mtime=time.time_ns(), etag=etag)

    def release(self) -> None:
        self.data = None
        self.size = 0
