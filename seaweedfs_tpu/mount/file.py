"""File node + open-handle ops.

Reference: weed/filesys/file.go (Attr/Setattr-truncate/addChunks),
filehandle.go (Read via chunk-view gather, Write via dirty pages, Flush
persisting the entry through the filer).
"""

from __future__ import annotations

import asyncio
import time

from ..filer.entry import Entry
from ..filer.filechunks import (FileChunk, non_overlapping_visible_intervals,
                                total_size, view_from_visibles)
from .dir import MountError
from .dirty_pages import ContinuousDirtyPages


class File:
    def __init__(self, name: str, dir: "Dir", entry: Entry | None = None):
        self.name = name
        self.dir = dir
        self.wfs = dir.wfs
        self.entry = entry
        self._view_cache = None  # entryViewCache (file.go:32)
        self.is_open = False

    @property
    def full_path(self) -> str:
        return f"{self.dir.path.rstrip('/')}/{self.name}"

    async def maybe_load_entry(self) -> Entry:
        """file.go maybeLoadEntry (:76-93)."""
        if self.entry is None or not self.is_open:
            entry = self.wfs.filer.find_entry(self.full_path)
            if entry is None:
                raise MountError("ENOENT", self.full_path)
            self.entry = entry
        return self.entry

    async def attr(self) -> dict:
        """file.go Attr (:40-66): size is chunk extent."""
        entry = await self.maybe_load_entry()
        return {"mode": entry.attr.mode, "size": total_size(entry.chunks),
                "mtime": entry.attr.mtime, "uid": entry.attr.uid,
                "gid": entry.attr.gid}

    def open(self, uid: int = 0, gid: int = 0) -> "FileHandle":
        """file.go Open (:68-74): register a handle."""
        self.is_open = True
        fh = FileHandle(self, uid, gid)
        # registry keyed by a unique handle id: concurrent opens of one
        # path must not clobber each other
        self.wfs.handles[id(fh)] = fh
        return fh

    def add_chunks(self, chunks: list[FileChunk]) -> None:
        """file.go addChunks (:139-147): append + invalidate view."""
        self.entry.chunks.extend(chunks)
        self._view_cache = None

    def views(self, offset: int, size: int):
        if self._view_cache is None:
            self._view_cache = non_overlapping_visible_intervals(
                self.entry.chunks)
        return view_from_visibles(self._view_cache, offset, size)

    async def setattr(self, size: int | None = None,
                      mode: int | None = None, uid: int | None = None,
                      gid: int | None = None,
                      mtime: float | None = None) -> None:
        """file.go Setattr (:95-137); truncation clips the chunk list."""
        entry = await self.maybe_load_entry()
        if size is not None and size < total_size(entry.chunks):
            kept: list[FileChunk] = []
            dropped: list[FileChunk] = []
            for c in entry.chunks:
                if c.offset >= size:
                    dropped.append(c)
                    continue
                if c.offset + c.size > size:
                    c.size = size - c.offset
                kept.append(c)
            entry.chunks = kept
            self._view_cache = None
            if dropped:
                self.wfs.filer.delete_chunks([c.file_id for c in dropped])
        if mode is not None:
            entry.attr.mode = (entry.attr.mode & ~0o7777) | (mode & 0o7777)
        if uid is not None:
            entry.attr.uid = uid
        if gid is not None:
            entry.attr.gid = gid
        if mtime is not None:
            entry.attr.mtime = mtime
        self.wfs.filer.update_entry(None, entry)
        self.wfs.cache_invalidate(self.full_path)

    # xattr passthrough (xattr.go)

    async def get_xattr(self, name: str) -> bytes:
        from .dir import _get_xattr
        return await _get_xattr(self.wfs, self.full_path, name)

    async def set_xattr(self, name: str, value: bytes) -> None:
        from .dir import _set_xattr
        await _set_xattr(self.wfs, self.full_path, name, value)

    async def list_xattr(self) -> list[str]:
        from .dir import _list_xattr
        return await _list_xattr(self.wfs, self.full_path)

    async def remove_xattr(self, name: str) -> None:
        from .dir import _remove_xattr
        await _remove_xattr(self.wfs, self.full_path, name)


class FileHandle:
    """filehandle.go:18-181."""

    def __init__(self, file: File, uid: int = 0, gid: int = 0):
        self.file = file
        self.uid = uid
        self.gid = gid
        self.dirty_pages = ContinuousDirtyPages(file)
        self.dirty_metadata = False

    async def read(self, offset: int, size: int) -> bytes:
        """filehandle.go Read (:49-77): clip views, gather chunk reads
        concurrently, assemble in logical order."""
        entry = await self.file.maybe_load_entry()
        end = min(offset + size, total_size(entry.chunks))
        if end <= offset:
            return b""
        views = self.file.views(offset, size)
        parts = await asyncio.gather(*(
            self.file.wfs.read_chunk(v.file_id, v.offset, v.size)
            for v in views))
        # zero-filled buffer: sparse holes (incl. trailing ones) read as
        # zeros, consistent with the HTTP streamers
        buf = bytearray(end - offset)
        for v, part in zip(views, parts):
            at = v.logic_offset - offset
            buf[at:at + len(part)] = part
        return bytes(buf)

    async def write(self, offset: int, data: bytes) -> int:
        """filehandle.go Write (:80-113)."""
        await self.file.maybe_load_entry()
        flushed = await self.dirty_pages.add_page(offset, data)
        if flushed:
            self.file.add_chunks(flushed)
        self.dirty_metadata = True
        return len(data)

    async def flush(self) -> None:
        """filehandle.go Flush (:127-181): save dirty pages, persist the
        entry through the filer (CreateEntry dedups overwritten chunks)."""
        chunk = await self.dirty_pages.flush()
        if chunk is not None:
            self.file.add_chunks([chunk])
            self.dirty_metadata = True
        if not self.dirty_metadata:
            return
        entry = self.file.entry
        entry.attr.mtime = time.time()
        if not entry.attr.crtime:
            entry.attr.crtime = entry.attr.mtime
        self.file.wfs.filer.create_entry(entry)
        self.file.wfs.cache_invalidate(self.file.full_path)
        self.dirty_metadata = False

    async def release(self) -> None:
        """filehandle.go Release (:115-125)."""
        self.dirty_pages.release()
        self.file.is_open = False
        self.file.wfs.handles.pop(id(self), None)
