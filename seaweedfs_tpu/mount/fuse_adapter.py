"""Kernel FUSE bridge (optional).

Reference: weed/command/mount_std.go:26-139 wires filesys nodes into
bazil-fork fuse. Here the bridge targets the `fusepy` Operations API when
the library is present; the node layer itself (wfs/dir/file) carries all
semantics and is exercised in-proc by the tests, so environments without
a FUSE binding lose only the kernel hookup.
"""

from __future__ import annotations

import asyncio
import errno
import stat
import threading

from .dir import Dir, MountError
from .file import File
from .wfs import WFS, MountOptions

try:  # pragma: no cover - not installed in the build image
    from fuse import FUSE, FuseOSError, Operations
    KERNEL_BINDING = "fusepy"
except ImportError:
    # built-in /dev/fuse wire-protocol binding (fusekernel.py) — same
    # Operations surface, no third-party dependency
    from .fusekernel import FUSE, FuseOSError, Operations
    KERNEL_BINDING = "builtin"
HAVE_FUSE = True


def _errno_of(e: MountError) -> int:
    return getattr(errno, e.errno_name, errno.EIO)


class _LoopThread:
    """Run the async node ops on a dedicated event loop; FUSE callbacks
    arrive on kernel threads."""

    def __init__(self) -> None:
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       daemon=True)
        self.thread.start()

    def run(self, coro):
        try:
            return asyncio.run_coroutine_threadsafe(coro, self.loop).result()
        except MountError as e:
            raise FuseOSError(_errno_of(e)) from e

    def stop(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


class SeaweedFuseOps(Operations):  # pragma: no cover - needs a kernel
    """fusepy Operations over the WFS node tree."""

    def __init__(self, wfs: WFS):
        self.wfs = wfs
        self.lt = _LoopThread()
        self.lt.run(wfs.start())
        self._handles: dict[int, object] = {}
        self._next_fh = 1
        self._fh_lock = threading.Lock()

    def _alloc_fh(self, handle) -> int:
        # kernel callbacks run on concurrent threads (nothreads=False)
        with self._fh_lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = handle
            return fh

    def _node(self, path: str):
        if path in ("/", ""):
            return self.wfs.root
        parent, _, name = path.rstrip("/").rpartition("/")
        d = Dir(parent or "/", self.wfs)
        return self.lt.run(d.lookup(name))

    # -- metadata --

    def getattr(self, path, fh=None):
        node = self._node(path)
        if isinstance(node, Dir):
            a = self.lt.run(node.attr())
            return {"st_mode": stat.S_IFDIR | (a.mode & 0o7777),
                    "st_nlink": 2, "st_uid": a.uid, "st_gid": a.gid,
                    "st_mtime": a.mtime, "st_ctime": a.crtime, "st_size": 0}
        a = self.lt.run(node.attr())
        return {"st_mode": stat.S_IFREG | (a["mode"] & 0o7777),
                "st_nlink": 1, "st_size": a["size"], "st_uid": a["uid"],
                "st_gid": a["gid"], "st_mtime": a["mtime"]}

    def readdir(self, path, fh):
        d = self._node(path)
        entries = self.lt.run(d.read_dir_all())
        return [".", ".."] + [e.name for e in entries]

    def mkdir(self, path, mode):
        parent, _, name = path.rstrip("/").rpartition("/")
        self.lt.run(Dir(parent or "/", self.wfs).mkdir(name, mode))

    def rmdir(self, path):
        parent, _, name = path.rstrip("/").rpartition("/")
        self.lt.run(Dir(parent or "/", self.wfs).remove(name, is_dir=True))

    def unlink(self, path):
        parent, _, name = path.rstrip("/").rpartition("/")
        self.lt.run(Dir(parent or "/", self.wfs).remove(name))

    def rename(self, old, new):
        op, _, on = old.rstrip("/").rpartition("/")
        np, _, nn = new.rstrip("/").rpartition("/")
        self.lt.run(Dir(op or "/", self.wfs).rename(
            on, Dir(np or "/", self.wfs), nn))

    def chmod(self, path, mode):
        node = self._node(path)
        self.lt.run(node.setattr(mode=mode))

    def chown(self, path, uid, gid):
        node = self._node(path)
        self.lt.run(node.setattr(uid=uid, gid=gid))

    def truncate(self, path, length, fh=None):
        node = self._node(path)
        self.lt.run(node.setattr(size=length))

    # -- file I/O --

    def create(self, path, mode, fi=None):
        parent, _, name = path.rstrip("/").rpartition("/")
        _, handle = self.lt.run(
            Dir(parent or "/", self.wfs).create(name, mode))
        return self._alloc_fh(handle)

    def open(self, path, flags):
        node = self._node(path)
        if not isinstance(node, File):
            raise FuseOSError(errno.EISDIR)
        return self._alloc_fh(node.open())

    def read(self, path, size, offset, fh):
        return self.lt.run(self._handles[fh].read(offset, size))

    def write(self, path, data, offset, fh):
        return self.lt.run(self._handles[fh].write(offset, data))

    def flush(self, path, fh):
        if fh in self._handles:
            self.lt.run(self._handles[fh].flush())
        return 0

    def release(self, path, fh):
        handle = self._handles.pop(fh, None)
        if handle is not None:
            self.lt.run(handle.flush())
            self.lt.run(handle.release())
        return 0

    # -- xattr --

    def getxattr(self, path, name, position=0):
        # missing path propagates as ENOENT; missing attr is already
        # ENODATA from the node layer
        return self.lt.run(self._node(path).get_xattr(name))

    def setxattr(self, path, name, value, options, position=0):
        self.lt.run(self._node(path).set_xattr(name, value))

    def listxattr(self, path):
        return self.lt.run(self._node(path).list_xattr())

    def removexattr(self, path, name):
        self.lt.run(self._node(path).remove_xattr(name))

    def destroy(self, path):
        self.lt.run(self.wfs.close())
        self.lt.stop()


def mount(filer, master_url: str, mountpoint: str,
          option: MountOptions | None = None,
          foreground: bool = True) -> None:  # pragma: no cover
    """command/mount_std.go runMount equivalent."""
    wfs = WFS(filer, master_url, option)
    FUSE(SeaweedFuseOps(wfs), mountpoint, foreground=foreground,
         nothreads=False, allow_other=False)
