"""Kernel FUSE binding over /dev/fuse — no third-party library.

Reference: weed/command/mount_std.go:26-139 hooks the filesystem into
the kernel via the bazil fuse fork (which itself speaks the FUSE wire
protocol over /dev/fuse). This module is that kernel hookup for the
tpu repo, implemented directly against the FUSE 7.x wire protocol
(include/uapi/linux/fuse.h layouts re-derived from the protocol docs):
open /dev/fuse, mount(2) (fusermount fallback), then a request loop of
fuse_in_header + opcode body -> fuse_out_header + reply body.

The public surface is fusepy-compatible (`FUSE`, `Operations`,
`FuseOSError`) because `fuse_adapter.SeaweedFuseOps` targets that API;
when fusepy is absent the adapter falls back to this binding, so the
kernel VFS -> WFS -> filer -> volume path works out of the box.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import socket
import stat as stat_m
import struct
import subprocess
import threading

from ..util import glog

# ---------------------------------------------------------------------------
# fusepy-compatible surface
# ---------------------------------------------------------------------------


class FuseOSError(OSError):
    def __init__(self, eno: int):
        super().__init__(eno, os.strerror(eno))


class Operations:  # minimal default base, fusepy-style
    def __call__(self, op, *args):
        if not hasattr(self, op):
            raise FuseOSError(errno.ENOSYS)
        return getattr(self, op)(*args)


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

FUSE_KERNEL_VERSION = 7
FUSE_KERNEL_MINOR = 31          # the layout set this module speaks

# opcodes
OP_LOOKUP, OP_FORGET, OP_GETATTR, OP_SETATTR = 1, 2, 3, 4
OP_MKDIR, OP_UNLINK, OP_RMDIR, OP_RENAME = 9, 10, 11, 12
OP_OPEN, OP_READ, OP_WRITE, OP_STATFS, OP_RELEASE = 14, 15, 16, 17, 18
OP_FSYNC, OP_SETXATTR, OP_GETXATTR, OP_LISTXATTR = 20, 21, 22, 23
OP_REMOVEXATTR, OP_FLUSH, OP_INIT, OP_OPENDIR = 24, 25, 26, 27
OP_READDIR, OP_RELEASEDIR, OP_FSYNCDIR = 28, 29, 30
OP_ACCESS, OP_CREATE, OP_INTERRUPT = 34, 35, 36
OP_DESTROY, OP_BATCH_FORGET, OP_RENAME2 = 38, 42, 45

_NO_REPLY = {OP_FORGET, OP_BATCH_FORGET, OP_INTERRUPT}

IN_HEADER = struct.Struct("<IIQQIIII")      # len op unique nodeid uid gid pid pad
OUT_HEADER = struct.Struct("<IiQ")          # len error unique

# fuse_attr (7.9+ layout, 88 bytes)
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")

ENTRY_OUT = struct.Struct("<QQQQII")        # nodeid gen entry_valid attr_valid nsecs
ATTR_OUT = struct.Struct("<QII")            # attr_valid attr_valid_nsec dummy
OPEN_OUT = struct.Struct("<QII")            # fh open_flags padding
WRITE_OUT = struct.Struct("<II")
GETXATTR_OUT = struct.Struct("<II")
INIT_OUT = struct.Struct("<IIIIHHIIHHI28x")  # ..flags2 + unused[7] tail
STATFS_OUT = struct.Struct("<QQQQQIIII24x")

MAX_WRITE = 128 * 1024
FUSE_BIG_WRITES = 1 << 5
ATTR_TTL = 1.0


def _pack_attr(ino: int, a: dict) -> bytes:
    mode = a["st_mode"]
    size = a.get("st_size", 0)
    mt = int(a.get("st_mtime", 0))
    ct = int(a.get("st_ctime", mt))
    return ATTR.pack(ino, size, (size + 511) // 512, mt, mt, ct,
                     0, 0, 0, mode, a.get("st_nlink", 1),
                     a.get("st_uid", 0), a.get("st_gid", 0), 0, 4096, 0)


def _entry_reply(ino: int, a: dict) -> bytes:
    ttl = int(ATTR_TTL)
    nsec = int((ATTR_TTL - ttl) * 1e9)
    return ENTRY_OUT.pack(ino, 0, ttl, ttl, nsec, nsec) + _pack_attr(ino, a)


def _dirent(ino: int, off: int, name: bytes, dtype: int) -> bytes:
    ent = struct.pack("<QQII", ino, off, len(name), dtype) + name
    pad = (8 - len(ent) % 8) % 8
    return ent + b"\0" * pad


# ---------------------------------------------------------------------------
# mounting
# ---------------------------------------------------------------------------

_libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                    use_errno=True)
_libc.mount.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                        ctypes.c_ulong, ctypes.c_char_p]
MS_NOSUID, MS_NODEV = 0x2, 0x4


def _mount_dev_fuse(mountpoint: str, allow_other: bool) -> int:
    """Open /dev/fuse and mount(2) it; fall back to fusermount's
    _FUSE_COMMFD fd-passing protocol when mount(2) is not permitted."""
    try:
        fd = os.open("/dev/fuse", os.O_RDWR)
    except OSError as e:
        raise RuntimeError(f"cannot open /dev/fuse: {e}") from e
    opts = (f"fd={fd},rootmode=40000,user_id={os.getuid()},"
            f"group_id={os.getgid()},default_permissions")
    if allow_other:
        opts += ",allow_other"
    r = _libc.mount(b"seaweedfs_tpu", mountpoint.encode(), b"fuse",
                    MS_NOSUID | MS_NODEV, opts.encode())
    if r == 0:
        return fd
    os.close(fd)
    return _mount_fusermount(mountpoint, allow_other)


def _mount_fusermount(mountpoint: str, allow_other: bool) -> int:
    """fusermount passes the mounted /dev/fuse fd back over a unix
    socketpair named by $_FUSE_COMMFD (SCM_RIGHTS)."""
    s0, s1 = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    opts = "rootmode=40000,default_permissions"
    if allow_other:
        opts += ",allow_other"
    env = dict(os.environ, _FUSE_COMMFD=str(s1.fileno()))
    proc = subprocess.Popen(
        ["fusermount", "-o", opts, "--", mountpoint],
        env=env, pass_fds=(s1.fileno(),))
    s1.close()
    msg, anc, _, _ = s0.recvmsg(4, socket.CMSG_SPACE(4))
    proc.wait()
    s0.close()
    for level, ctype, data in anc:
        if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
            return struct.unpack("<i", data[:4])[0]
    raise RuntimeError(
        f"fusermount did not hand back a fd (exit {proc.returncode})")


def unmount(mountpoint: str) -> None:
    # non-lazy first: it aborts the fuse connection so the serve loop's
    # blocked read returns ENODEV immediately; MNT_DETACH only detaches
    if _libc.umount2(mountpoint.encode(), 0) == 0:
        return
    if _libc.umount2(mountpoint.encode(), 2) == 0:  # MNT_DETACH
        return
    # already-unmounted is fine (the serve loop also unmounts on exit)
    subprocess.call(["fusermount", "-u", "-z", "--", mountpoint],
                    stderr=subprocess.DEVNULL)


# ---------------------------------------------------------------------------
# the kernel session
# ---------------------------------------------------------------------------


class FUSE:
    """Mount `operations` (fusepy path-based API) at `mountpoint` and
    serve the kernel request loop until unmounted."""

    def __init__(self, operations, mountpoint: str, foreground: bool = True,
                 nothreads: bool = True, allow_other: bool = False,
                 ready_event: threading.Event | None = None):
        self.ops = operations
        self.mountpoint = os.path.abspath(mountpoint)
        # nodeid <-> path; nodeid doubles as st_ino
        self._paths: dict[int, str] = {1: "/"}
        self._ids: dict[str, int] = {"/": 1}
        self._next_id = 2
        self._lock = threading.Lock()
        self._fd = _mount_dev_fuse(self.mountpoint, allow_other)
        self._destroyed = False
        if ready_event is not None:
            ready_event.set()
        try:
            self._loop()
        finally:
            try:
                os.close(self._fd)
            except OSError:
                pass
            if not self._destroyed:
                unmount(self.mountpoint)
            if hasattr(self.ops, "destroy"):
                try:
                    self.ops.destroy(self.mountpoint)
                except Exception as e:
                    # teardown must finish unmounting either way, but a
                    # destroy() fault means dirty pages may not have
                    # flushed — that must be visible
                    glog.warning("fuse destroy(%s) failed: %r",
                                 self.mountpoint, e)

    # -- node table --

    def _id_of(self, path: str) -> int:
        with self._lock:
            nid = self._ids.get(path)
            if nid is None:
                nid = self._next_id
                self._next_id += 1
                self._ids[path] = nid
                self._paths[nid] = path
            return nid

    def _rename_tree(self, old: str, new: str) -> None:
        with self._lock:
            for nid, p in list(self._paths.items()):
                if p == old or p.startswith(old + "/"):
                    np = new + p[len(old):]
                    self._ids.pop(p, None)
                    self._paths[nid] = np
                    self._ids[np] = nid

    def _drop(self, path: str) -> None:
        with self._lock:
            nid = self._ids.pop(path, None)
            if nid is not None:
                self._paths.pop(nid, None)

    @staticmethod
    def _join(parent: str, name: str) -> str:
        return (parent.rstrip("/") or "") + "/" + name

    # -- request loop --

    def _loop(self) -> None:
        bufsize = MAX_WRITE + 4096
        while True:
            try:
                req = os.read(self._fd, bufsize)
            except OSError as e:
                if e.errno == errno.EINTR:
                    continue
                # ENODEV: unmounted; EBADF: fd closed
                break
            if not req:
                break
            (length, op, unique, nodeid, uid, gid, pid,
             _pad) = IN_HEADER.unpack_from(req)
            body = req[IN_HEADER.size:length]
            try:
                out = self._dispatch(op, nodeid, body)
            except FuseOSError as e:
                out = -(e.errno or errno.EIO)
            except OSError as e:
                out = -(e.errno or errno.EIO)
            except Exception:
                out = -errno.EIO
            if op in _NO_REPLY:
                continue
            if isinstance(out, int) and out < 0:
                reply = OUT_HEADER.pack(OUT_HEADER.size, out, unique)
            else:
                payload = out or b""
                reply = OUT_HEADER.pack(
                    OUT_HEADER.size + len(payload), 0, unique) + payload
            try:
                os.write(self._fd, reply)
            except OSError as e:
                if e.errno in (errno.ENOENT, errno.EINTR):
                    continue        # request was interrupted/aborted
                break
            if op == OP_DESTROY:
                self._destroyed = True
                break

    # -- dispatch --

    def _dispatch(self, op, nodeid, body):
        path = self._paths.get(nodeid, "/")
        if op == OP_INIT:
            major, minor, max_ra, flags = struct.unpack_from("<IIII", body)
            if major != FUSE_KERNEL_VERSION:
                # kernel re-INITs when we reply just our major
                return struct.pack("<I", FUSE_KERNEL_VERSION) + b"\0" * 60
            return INIT_OUT.pack(
                FUSE_KERNEL_VERSION, min(minor, FUSE_KERNEL_MINOR),
                max_ra, flags & FUSE_BIG_WRITES, 12, 8, MAX_WRITE, 1,
                0, 0, 0)
        if op == OP_LOOKUP:
            name = body.rstrip(b"\0").decode()
            child = self._join(path, name)
            a = self.ops.getattr(child, None)
            return _entry_reply(self._id_of(child), a)
        if op == OP_GETATTR:
            a = self.ops.getattr(path, None)
            ttl = int(ATTR_TTL)
            return ATTR_OUT.pack(ttl, 0, 0) + _pack_attr(nodeid, a)
        if op == OP_SETATTR:
            (valid, _pad, fh, size, _lo, _at, mt, _ct, _ans, _mns,
             _cns, mode, _u4, uid, gid, _u5) = struct.unpack_from(
                "<IIQQQQQQIIIIIIII", body)
            FATTR_MODE, FATTR_UID, FATTR_GID, FATTR_SIZE = 1, 2, 4, 8
            if valid & FATTR_SIZE:
                self.ops.truncate(path, size, None)
            if valid & FATTR_MODE:
                self.ops.chmod(path, mode)
            if valid & (FATTR_UID | FATTR_GID):
                a0 = self.ops.getattr(path, None)
                self.ops.chown(
                    path,
                    uid if valid & FATTR_UID else a0["st_uid"],
                    gid if valid & FATTR_GID else a0["st_gid"])
            a = self.ops.getattr(path, None)
            return ATTR_OUT.pack(int(ATTR_TTL), 0, 0) + _pack_attr(nodeid, a)
        if op == OP_MKDIR:
            mode, _umask = struct.unpack_from("<II", body)
            name = body[8:].rstrip(b"\0").decode()
            child = self._join(path, name)
            self.ops.mkdir(child, mode)
            return _entry_reply(self._id_of(child),
                                self.ops.getattr(child, None))
        if op in (OP_UNLINK, OP_RMDIR):
            name = body.rstrip(b"\0").decode()
            child = self._join(path, name)
            (self.ops.rmdir if op == OP_RMDIR else self.ops.unlink)(child)
            self._drop(child)
            return b""
        if op in (OP_RENAME, OP_RENAME2):
            off = 8 if op == OP_RENAME else 16
            (newdir,) = struct.unpack_from("<Q", body)
            oldn, newn = body[off:].rstrip(b"\0").split(b"\0")[:2]
            old = self._join(path, oldn.decode())
            new = self._join(self._paths.get(newdir, "/"), newn.decode())
            self.ops.rename(old, new)
            self._rename_tree(old, new)
            return b""
        if op in (OP_OPEN, OP_OPENDIR):
            (flags, _) = struct.unpack_from("<II", body)
            if op == OP_OPENDIR:
                return OPEN_OUT.pack(0, 0, 0)
            fh = self.ops.open(path, flags)
            return OPEN_OUT.pack(fh, 0, 0)
        if op == OP_CREATE:
            flags, mode, _umask, _ = struct.unpack_from("<IIII", body)
            name = body[16:].rstrip(b"\0").decode()
            child = self._join(path, name)
            fh = self.ops.create(child, mode & 0o7777)
            a = self.ops.getattr(child, fh)
            return (_entry_reply(self._id_of(child), a)
                    + OPEN_OUT.pack(fh, 0, 0))
        if op == OP_READ:
            fh, off, size = struct.unpack_from("<QQI", body)
            return bytes(self.ops.read(path, size, off, fh))
        if op == OP_WRITE:
            fh, off, size, _wf = struct.unpack_from("<QQII", body)
            data = body[struct.calcsize("<QQIIQII"):]
            if len(data) < size:       # header grew in 7.9; recompute
                data = body[-size:]
            n = self.ops.write(path, data[:size], off, fh)
            return WRITE_OUT.pack(n, 0)
        if op == OP_READDIR:
            fh, off, size = struct.unpack_from("<QQI", body)
            names = self.ops.readdir(path, fh)
            out = b""
            for i, name in enumerate(names[off:], start=off + 1):
                if name in (".", ".."):
                    ino, dtype = 1, stat_m.S_IFDIR >> 12
                else:
                    child = self._join(path, name)
                    ino = self._id_of(child)
                    dtype = 0
                ent = _dirent(ino, i, name.encode(), dtype)
                if len(out) + len(ent) > size:
                    break
                out += ent
            return out
        if op == OP_FLUSH:
            fh, = struct.unpack_from("<Q", body)
            self.ops.flush(path, fh)
            return b""
        if op in (OP_RELEASE, OP_RELEASEDIR):
            fh, = struct.unpack_from("<Q", body)
            if op == OP_RELEASE:
                self.ops.release(path, fh)
            return b""
        if op in (OP_FSYNC, OP_FSYNCDIR):
            fh, = struct.unpack_from("<Q", body)
            if op == OP_FSYNC and hasattr(self.ops, "flush"):
                self.ops.flush(path, fh)
            return b""
        if op == OP_STATFS:
            return STATFS_OUT.pack(1 << 30, 1 << 29, 1 << 29, 1 << 20,
                                   1 << 19, 4096, 255, 4096, 0)
        if op == OP_ACCESS:
            return b""
        if op == OP_GETXATTR:
            size, _ = struct.unpack_from("<II", body)
            name = body[8:].rstrip(b"\0").decode()
            try:
                val = self.ops.getxattr(path, name)
            except FuseOSError:
                raise
            if size == 0:
                return GETXATTR_OUT.pack(len(val), 0)
            if len(val) > size:
                return -errno.ERANGE
            return bytes(val)
        if op == OP_LISTXATTR:
            size, _ = struct.unpack_from("<II", body)
            names = self.ops.listxattr(path)
            blob = b"".join(n.encode() + b"\0" for n in names)
            if size == 0:
                return GETXATTR_OUT.pack(len(blob), 0)
            if len(blob) > size:
                return -errno.ERANGE
            return blob
        if op == OP_SETXATTR:
            vsize, _flags = struct.unpack_from("<II", body)
            rest = body[8:]
            nul = rest.index(b"\0")
            name = rest[:nul].decode()
            value = rest[nul + 1:nul + 1 + vsize]
            self.ops.setxattr(path, name, value, 0)
            return b""
        if op == OP_REMOVEXATTR:
            name = body.rstrip(b"\0").decode()
            self.ops.removexattr(path, name)
            return b""
        if op == OP_DESTROY:
            return b""
        if op in _NO_REPLY:
            return b""
        return -errno.ENOSYS
