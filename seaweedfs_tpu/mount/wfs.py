"""WFS: the mount-wide state shared by all nodes.

Reference: weed/filesys/wfs.go:45-212 (options, handle registry, buffer
pool, deletion fan-out wfs_deletion.go:15-72). Nodes resolve metadata
through an in-proc Filer (the reference goes through filer gRPC; the node
semantics are identical) and chunk data through the master/volume tier via
WeedClient.
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from dataclasses import dataclass, field

from ..filer.filer import Filer
from ..util.client import WeedClient
from .dir import Dir


@dataclass
class MountOptions:
    """wfs.go Option struct (:25-43)."""
    collection: str = ""
    replication: str = ""
    ttl: str = ""
    chunk_size_limit: int = 4 * 1024 * 1024
    data_center: str = ""
    entry_cache_ttl: float = 1.0
    gc_interval: float = 0.5


class WFS:
    def __init__(self, filer: Filer, master_url: str,
                 option: MountOptions | None = None):
        self.filer = filer
        self.master_url = master_url
        self.option = option or MountOptions()
        self.client = WeedClient(master_url)
        self.root = Dir("/", self)
        # open-handle registry keyed by full path (wfs.go:86-118)
        self.handles: dict[str, object] = {}
        # attr/entry cache with TTL (the reference leans on fuse attr
        # Valid=1s; here an explicit (entry, deadline) cache)
        self._entry_cache: dict[str, tuple[object, float]] = {}
        self._gc_task: asyncio.Task | None = None
        filer.chunk_deleter = self._queue_chunk_deletes
        self._pending_fids: list[str] = []

    async def start(self) -> None:
        await self.client.__aenter__()
        self._gc_task = asyncio.create_task(self._gc_loop())

    async def close(self) -> None:
        if self._gc_task:
            self._gc_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._gc_task
        await self.drain_deletes()
        await self.client.__aexit__()

    # ---- chunk data plane ----

    async def save_data_as_chunk(self, data: bytes) -> tuple[str, str]:
        """assign + upload; returns (fid, etag) (dirty_page.go:179-210)."""
        a = await self.client.assign(
            collection=self.option.collection,
            replication=self.option.replication,
            ttl=self.option.ttl, data_center=self.option.data_center)
        res = await self.client.upload(a["fid"], a["url"], data,
                                       ttl=self.option.ttl,
                                       auth=a.get("auth", ""))
        return a["fid"], res.get("eTag", "")

    async def read_chunk(self, fid: str, offset: int, size: int) -> bytes:
        return await self.client.read(fid, offset=offset, size=size)

    # ---- deletion fan-out (wfs_deletion.go:15-72) ----

    def _queue_chunk_deletes(self, fids: list[str]) -> None:
        self._pending_fids.extend(fids)

    async def drain_deletes(self) -> int:
        fids, self._pending_fids = self._pending_fids, []
        if not fids:
            return 0
        return await self.client.delete_fids(fids)

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.option.gc_interval)
            with contextlib.suppress(Exception):
                await self.drain_deletes()

    # ---- entry cache ----

    def cache_get(self, path: str):
        hit = self._entry_cache.get(path)
        if hit and time.monotonic() < hit[1]:
            return hit[0]
        self._entry_cache.pop(path, None)
        return None

    def cache_set(self, path: str, entry) -> None:
        self._entry_cache[path] = (
            entry, time.monotonic() + self.option.entry_cache_ttl)

    def cache_invalidate(self, path: str) -> None:
        self._entry_cache.pop(path, None)
