"""native subpackage."""
