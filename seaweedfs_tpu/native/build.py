"""Build-on-first-use for the native host library.

The reference gets its host-side speed from amd64 assembly inside Go deps
(klauspost/crc32, klauspost/reedsolomon); our host-side native surface is a
small C library compiled locally with g++. No network, no pip.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libswtpu_native.so")
_SOURCES = [os.path.join(_DIR, "crc32c.c"),
            os.path.join(_DIR, "needle_map.c"),
            os.path.join(_DIR, "gf256.c")]
_lock = threading.Lock()
_lib = None
_failed = False


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    return any(os.path.exists(s) and os.path.getmtime(s) > so_mtime
               for s in _SOURCES)


def load() -> ctypes.CDLL | None:
    """Return the native library, building it if needed; None if unavailable."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            if _needs_build():
                srcs = [s for s in _SOURCES if os.path.exists(s)]
                # no -mavx2 etc: SIMD paths use per-function target
                # attributes + __builtin_cpu_supports runtime dispatch, so
                # a cached .so stays safe on a different host
                cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", _SO] + srcs
                subprocess.run(cmd, check=True, capture_output=True,
                               cwd=_DIR, timeout=120)
            lib = ctypes.CDLL(_SO)
            lib.swtpu_crc32c.restype = ctypes.c_uint32
            lib.swtpu_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_char_p,
                                         ctypes.c_size_t]
            u8p = ctypes.POINTER(ctypes.c_uint8)
            for fname in ("swtpu_gf256_transform",
                          "swtpu_gf256_transform_scalar"):
                fn = getattr(lib, fname)
                fn.restype = None
                fn.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                               ctypes.POINTER(u8p), ctypes.POINTER(u8p),
                               ctypes.c_size_t]
            # build the GF tables now, single-threaded under _lock: the
            # transforms run GIL-free and must never race a lazy init
            lib.swtpu_gf256_init.restype = None
            lib.swtpu_gf256_init()
            u64p = ctypes.POINTER(ctypes.c_uint64)
            u32p = ctypes.POINTER(ctypes.c_uint32)
            lib.swtpu_nm_new.restype = ctypes.c_void_p
            lib.swtpu_nm_free.argtypes = [ctypes.c_void_p]
            lib.swtpu_nm_set.restype = ctypes.c_int
            lib.swtpu_nm_set.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                         ctypes.c_uint32, ctypes.c_uint32,
                                         u32p, u32p]
            lib.swtpu_nm_get.restype = ctypes.c_int
            lib.swtpu_nm_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                         u32p, u32p]
            lib.swtpu_nm_len.restype = ctypes.c_uint64
            lib.swtpu_nm_len.argtypes = [ctypes.c_void_p]
            lib.swtpu_nm_scan.restype = ctypes.c_uint64
            lib.swtpu_nm_scan.argtypes = [ctypes.c_void_p, u64p, u64p,
                                          u32p, u32p, ctypes.c_uint64]
            _lib = lib
        except Exception:
            _failed = True
        return _lib
