/* CRC32-Castagnoli for the needle read/write path.
 *
 * TPU-native replacement for the reference's klauspost/crc32 SSE4.2
 * dependency (weed/storage/needle/crc.go). Hardware CRC32C via SSE4.2 when
 * the CPU supports it, slicing-by-8 table fallback otherwise.
 *
 * Built by seaweedfs_tpu/native/build.py:
 *   g++ -O3 -shared -fPIC -msse4.2 crc32c.c -o libswtpu_native.so
 */

#include <stdint.h>
#include <stddef.h>

#if defined(__x86_64__) || defined(__i386__)
#include <nmmintrin.h>
#define HAVE_SSE42_INTRIN 1
#endif

/* built with g++: exported symbols must not be C++-mangled or the ctypes
 * lookup in build.py fails and the whole library silently degrades to the
 * pure-Python fallbacks */
#ifdef __cplusplus
extern "C" {
#endif

#define POLY 0x82f63b78u /* reflected Castagnoli */

static uint32_t table[8][256];
static int table_ready = 0;

static void init_table(void) {
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (POLY ^ (c >> 1)) : (c >> 1);
        table[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = table[0][i];
        for (int s = 1; s < 8; s++) {
            c = table[0][c & 0xff] ^ (c >> 8);
            table[s][i] = c;
        }
    }
    table_ready = 1;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t *buf, size_t len) {
    if (!table_ready) init_table();
    crc = ~crc;
    while (len && ((uintptr_t)buf & 7)) {
        crc = table[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
        len--;
    }
    while (len >= 8) {
        uint64_t w;
        __builtin_memcpy(&w, buf, 8);
        w ^= crc;
        crc = table[7][w & 0xff] ^ table[6][(w >> 8) & 0xff] ^
              table[5][(w >> 16) & 0xff] ^ table[4][(w >> 24) & 0xff] ^
              table[3][(w >> 32) & 0xff] ^ table[2][(w >> 40) & 0xff] ^
              table[1][(w >> 48) & 0xff] ^ table[0][(w >> 56) & 0xff];
        buf += 8;
        len -= 8;
    }
    while (len--) crc = table[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
    return ~crc;
}

#ifdef HAVE_SSE42_INTRIN
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t *buf, size_t len) {
    crc = ~crc;
    while (len && ((uintptr_t)buf & 7)) {
        crc = _mm_crc32_u8(crc, *buf++);
        len--;
    }
    uint64_t c64 = crc;
    while (len >= 8) {
        uint64_t w;
        __builtin_memcpy(&w, buf, 8);
        c64 = _mm_crc32_u64(c64, w);
        buf += 8;
        len -= 8;
    }
    crc = (uint32_t)c64;
    while (len--) crc = _mm_crc32_u8(crc, *buf++);
    return ~crc;
}
#endif

uint32_t swtpu_crc32c(uint32_t crc, const uint8_t *buf, size_t len) {
#ifdef HAVE_SSE42_INTRIN
    if (__builtin_cpu_supports("sse4.2")) return crc32c_hw(crc, buf, len);
#endif
    return crc32c_sw(crc, buf, len);
}

#ifdef __cplusplus
}
#endif
