/* GF(2^8) Reed-Solomon matrix transform — native host kernel.
 *
 * Host-side analog of the amd64 assembly inside klauspost/reedsolomon
 * (galois_amd64.s): the reference's only native-accelerated component
 * besides crc32 (SURVEY.md §2b). Field: x^8+x^4+x^3+x^2+1 (0x11D), the
 * same field as seaweedfs_tpu/ec/gf.py, so outputs are bit-identical to
 * the numpy oracle and the TPU Pallas kernel.
 *
 * One generic entry point covers encode (consts = 4x10 parity matrix) and
 * reconstruct (consts = wanted-rows x present-rows recovery matrix):
 *
 *   out[r] = XOR_j gfmul(consts[r*k + j], in[j])     elementwise over n
 *
 * Fast path: AVX2 PSHUFB over 4-bit nibble lookup tables (the klauspost
 * idiom — two 16-byte tables per coefficient, 32 bytes per step). Portable
 * fallback: per-coefficient 256-byte multiplication tables.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define HAVE_X86 1
#endif

#ifdef __cplusplus
extern "C" {
#endif

static uint8_t GF_MUL[256][256];
static int gf_ready = 0;

/* Called once from build.py under its load lock BEFORE any transform is
 * reachable — the lazy gf_ready check alone would be a data race, since
 * ctypes drops the GIL and concurrent EC reads call in from two threads. */
void swtpu_gf256_init(void) {
    if (gf_ready) return;
    uint8_t exp[512];
    int log[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp[i] = (uint8_t)x;
        log[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    memcpy(exp + 255, exp, 255);
    memset(GF_MUL, 0, sizeof(GF_MUL));
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            GF_MUL[a][b] = exp[log[a] + log[b]];
    gf_ready = 1;
}

/* scalar fallback: table lookup per byte */
static void row_scalar(const uint8_t *coefs, int k,
                       const uint8_t *const *in, uint8_t *out, size_t n) {
    memset(out, 0, n);
    for (int j = 0; j < k; j++) {
        uint8_t c = coefs[j];
        if (c == 0) continue;
        const uint8_t *tbl = GF_MUL[c];
        const uint8_t *src = in[j];
        if (c == 1) {
            for (size_t i = 0; i < n; i++) out[i] ^= src[i];
        } else {
            for (size_t i = 0; i < n; i++) out[i] ^= tbl[src[i]];
        }
    }
}

#ifdef HAVE_X86
__attribute__((target("avx2")))
static void row_avx2(const uint8_t *coefs, int k,
                     const uint8_t *const *in, uint8_t *out, size_t n) {
    /* nibble tables per coefficient: lo[x]=c*x, hi[x]=c*(x<<4), x in 0..15 */
    memset(out, 0, n);
    const __m256i mask = _mm256_set1_epi8(0x0f);
    for (int j = 0; j < k; j++) {
        uint8_t c = coefs[j];
        if (c == 0) continue;
        const uint8_t *src = in[j];
        uint8_t lo[16], hi[16];
        for (int x = 0; x < 16; x++) {
            lo[x] = GF_MUL[c][x];
            hi[x] = GF_MUL[c][x << 4];
        }
        const __m256i tlo = _mm256_broadcastsi128_si256(
            _mm_loadu_si128((const __m128i *)lo));
        const __m256i thi = _mm256_broadcastsi128_si256(
            _mm_loadu_si128((const __m128i *)hi));
        size_t i = 0;
        for (; i + 32 <= n; i += 32) {
            __m256i v = _mm256_loadu_si256((const __m256i *)(src + i));
            __m256i o = _mm256_loadu_si256((const __m256i *)(out + i));
            __m256i vl = _mm256_and_si256(v, mask);
            __m256i vh = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
            o = _mm256_xor_si256(o, _mm256_shuffle_epi8(tlo, vl));
            o = _mm256_xor_si256(o, _mm256_shuffle_epi8(thi, vh));
            _mm256_storeu_si256((__m256i *)(out + i), o);
        }
        const uint8_t *tbl = GF_MUL[c];
        for (; i < n; i++) out[i] ^= tbl[src[i]];
    }
}
#endif

void swtpu_gf256_transform(const uint8_t *consts, int rows, int k,
                           const uint8_t *const *in, uint8_t *const *out,
                           size_t n) {
    swtpu_gf256_init();
    /* runtime dispatch: the .so may have been built on a different host
     * (it is cached on disk), so never assume AVX2 from compile flags */
#ifdef HAVE_X86
    if (__builtin_cpu_supports("avx2")) {
        for (int r = 0; r < rows; r++)
            row_avx2(consts + (size_t)r * k, k, in, out[r], n);
        return;
    }
#endif
    for (int r = 0; r < rows; r++)
        row_scalar(consts + (size_t)r * k, k, in, out[r], n);
}

/* Keep the scalar path linked even in AVX2 builds (used by tests via
 * swtpu_gf256_transform_scalar to cross-check the vector path). */
void swtpu_gf256_transform_scalar(const uint8_t *consts, int rows, int k,
                                  const uint8_t *const *in,
                                  uint8_t *const *out, size_t n) {
    swtpu_gf256_init();
    for (int r = 0; r < rows; r++)
        row_scalar(consts + (size_t)r * k, k, in, out[r], n);
}

#ifdef __cplusplus
}
#endif
