"""ctypes binding for the native GF(256) matrix transform (gf256.c).

The native analog of klauspost/reedsolomon's assembly hot loop
(ec_encoder.go:192 call path). Returns None-capable: callers fall back to
the numpy path when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import build

_u8p = ctypes.POINTER(ctypes.c_uint8)


def available() -> bool:
    lib = build.load()
    return lib is not None and hasattr(lib, "swtpu_gf256_transform")


def _as_ptr(a: np.ndarray) -> "ctypes._Pointer":
    return a.ctypes.data_as(_u8p)


def transform(consts: np.ndarray, inputs: list[np.ndarray],
              scalar: bool = False) -> list[np.ndarray]:
    """out[r] = XOR_j gfmul(consts[r,j], inputs[j]) over equal-length
    uint8 arrays. Returns freshly-allocated output arrays."""
    lib = build.load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    rows, k = consts.shape
    if len(inputs) != k:
        raise ValueError(f"consts is {rows}x{k} but got {len(inputs)} inputs")
    n = len(inputs[0])
    ins = [np.ascontiguousarray(x, dtype=np.uint8) for x in inputs]
    # hard length check: the C kernel reads exactly n bytes from every
    # input, and a short buffer would be a heap over-read (asserts vanish
    # under python -O, so raise)
    if any(len(x) != n for x in ins):
        raise ValueError("input shards have differing lengths")
    outs = [np.empty(n, dtype=np.uint8) for _ in range(rows)]
    c = np.ascontiguousarray(consts, dtype=np.uint8)
    in_ptrs = (_u8p * k)(*[_as_ptr(x) for x in ins])
    out_ptrs = (_u8p * rows)(*[_as_ptr(x) for x in outs])
    fn = (lib.swtpu_gf256_transform_scalar if scalar
          else lib.swtpu_gf256_transform)
    fn(_as_ptr(c), rows, k, in_ptrs, out_ptrs, n)
    return outs
