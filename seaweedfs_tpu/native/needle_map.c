/* Compact needle map — native per-volume key index.
 *
 * Role of the reference's CompactMap (weed/storage/needle_map/
 * compact_map.go:14-40,176-246): hold needleId -> (offset,size) for tens
 * of millions of needles per volume at ~16 bytes/entry (its perf test
 * budgets 100M entries — a Python dict at ~100+B/entry cannot).
 *
 * Design: open-addressing hash table with linear probing and 16-byte
 * entries (key 8B, offset 4B, size 4B), power-of-two capacity, grown at
 * 70% load. The reference exploits mostly-ascending keys with sorted
 * sections + binary search; a flat power-of-two table gets the same
 * memory footprint with O(1) worst-ish lookups and no sortedness
 * assumption, which suits the TPU build's batch-oriented loaders better.
 *
 * key 0 is reserved as the empty marker (SeaweedFS needle ids start at 1;
 * the Python wrapper keeps a sideband slot for key 0 just in case).
 * Deletes store the tombstone size value directly — identical semantics
 * to the .idx replay (TombstoneFileSize = 0xFFFFFFFF).
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
    uint64_t key;
    uint32_t offset;
    uint32_t size;
} nm_entry;

typedef struct {
    nm_entry *slots;
    uint64_t cap;     /* power of two */
    uint64_t used;    /* occupied slots (incl. tombstone-size entries) */
} nm_map;

static uint64_t nm_hash(uint64_t k) {
    /* splitmix64 finalizer: good avalanche for sequential ids */
    k ^= k >> 30; k *= 0xbf58476d1ce4e5b9ULL;
    k ^= k >> 27; k *= 0x94d049bb133111ebULL;
    k ^= k >> 31;
    return k;
}

void *swtpu_nm_new(void) {
    nm_map *m = (nm_map *)calloc(1, sizeof(nm_map));
    if (!m) return 0;
    m->cap = 1024;
    m->slots = (nm_entry *)calloc(m->cap, sizeof(nm_entry));
    if (!m->slots) { free(m); return 0; }
    return m;
}

void swtpu_nm_free(void *h) {
    nm_map *m = (nm_map *)h;
    if (!m) return;
    free(m->slots);
    free(m);
}

static nm_entry *nm_slot(nm_map *m, uint64_t key) {
    uint64_t mask = m->cap - 1;
    uint64_t i = nm_hash(key) & mask;
    while (m->slots[i].key != 0 && m->slots[i].key != key)
        i = (i + 1) & mask;
    return &m->slots[i];
}

static int nm_grow(nm_map *m) {
    uint64_t old_cap = m->cap;
    nm_entry *old = m->slots;
    nm_entry *fresh = (nm_entry *)calloc(old_cap * 2, sizeof(nm_entry));
    if (!fresh) return 0;
    m->slots = fresh;
    m->cap = old_cap * 2;
    for (uint64_t i = 0; i < old_cap; i++) {
        if (old[i].key != 0)
            *nm_slot(m, old[i].key) = old[i];
    }
    free(old);
    return 1;
}

/* returns: -1 alloc failure, 0 inserted new, 1 replaced existing;
 * old_offset/old_size receive the previous value when replacing */
int swtpu_nm_set(void *h, uint64_t key, uint32_t offset, uint32_t size,
                 uint32_t *old_offset, uint32_t *old_size) {
    nm_map *m = (nm_map *)h;
    if (key == 0) return -1;
    if ((m->used + 1) * 10 >= m->cap * 7) {
        if (!nm_grow(m)) return -1;
    }
    nm_entry *e = nm_slot(m, key);
    if (e->key == key) {
        if (old_offset) *old_offset = e->offset;
        if (old_size) *old_size = e->size;
        e->offset = offset;
        e->size = size;
        return 1;
    }
    e->key = key;
    e->offset = offset;
    e->size = size;
    m->used++;
    return 0;
}

int swtpu_nm_get(void *h, uint64_t key, uint32_t *offset, uint32_t *size) {
    nm_map *m = (nm_map *)h;
    if (key == 0) return 0;
    nm_entry *e = nm_slot(m, key);
    if (e->key != key) return 0;
    if (offset) *offset = e->offset;
    if (size) *size = e->size;
    return 1;
}

uint64_t swtpu_nm_len(void *h) {
    return ((nm_map *)h)->used;
}

/* copy up to max entries starting at cursor position *state into the out
 * arrays; returns number copied and advances *state (0 = start). */
uint64_t swtpu_nm_scan(void *h, uint64_t *state, uint64_t *keys,
                       uint32_t *offsets, uint32_t *sizes, uint64_t max) {
    nm_map *m = (nm_map *)h;
    uint64_t n = 0, i = *state;
    for (; i < m->cap && n < max; i++) {
        if (m->slots[i].key != 0) {
            keys[n] = m->slots[i].key;
            offsets[n] = m->slots[i].offset;
            sizes[n] = m->slots[i].size;
            n++;
        }
    }
    *state = i;
    return n;
}

#ifdef __cplusplus
}
#endif
