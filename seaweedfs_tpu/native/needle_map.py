"""ctypes binding for the native compact needle map (needle_map.c).

The memory-dense replacement for a Python dict in the per-volume needle
index — the role of the reference's CompactMap
(storage/needle_map/compact_map.go, perf-tested at 100M entries).
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import build

_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def available() -> bool:
    lib = build.load()
    return lib is not None and hasattr(lib, "swtpu_nm_new")


class NativeMap:
    """16-bytes-per-entry key -> (offset, size) map. key must be > 0."""

    def __init__(self):
        lib = build.load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.swtpu_nm_new()
        if not self._h:
            raise MemoryError("swtpu_nm_new failed")

    def close(self) -> None:
        if self._h:
            self._lib.swtpu_nm_free(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except (OSError, AttributeError):
            pass  # interpreter teardown: ctypes lib may be half-gone

    def set(self, key: int, offset: int, size: int) -> tuple[int, int] | None:
        """Insert/replace; returns the previous (offset, size) or None."""
        old_off = ctypes.c_uint32()
        old_size = ctypes.c_uint32()
        r = self._lib.swtpu_nm_set(self._h, key, offset, size,
                                   ctypes.byref(old_off),
                                   ctypes.byref(old_size))
        if r < 0:
            raise MemoryError("needle map allocation failure")
        if r == 1:
            return (old_off.value, old_size.value)
        return None

    def get(self, key: int) -> tuple[int, int] | None:
        off = ctypes.c_uint32()
        size = ctypes.c_uint32()
        if self._lib.swtpu_nm_get(self._h, key, ctypes.byref(off),
                                  ctypes.byref(size)):
            return (off.value, size.value)
        return None

    def __len__(self) -> int:
        return int(self._lib.swtpu_nm_len(self._h))

    def items(self, batch: int = 65536):
        """Yield (key, offset, size) in unspecified order."""
        state = ctypes.c_uint64(0)
        keys = np.empty(batch, np.uint64)
        offs = np.empty(batch, np.uint32)
        sizes = np.empty(batch, np.uint32)
        while True:
            n = self._lib.swtpu_nm_scan(
                self._h, ctypes.byref(state),
                keys.ctypes.data_as(_u64p), offs.ctypes.data_as(_u32p),
                sizes.ctypes.data_as(_u32p), batch)
            for i in range(int(n)):
                yield int(keys[i]), int(offs[i]), int(sizes[i])
            if n < batch:
                return
