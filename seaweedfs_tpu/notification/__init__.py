"""Filer meta-change notification publishers.

Reference: weed/notification/configuration.go (MessageQueue interface +
registry, exactly-one-enabled validation), kafka/kafka_queue.go,
log_queue.go, aws_sqs/, google_pub_sub/, gocdk_pub_sub/. Events are the
EventNotification shape from pb/filer.proto (old_entry/new_entry/
delete_chunks/new_parent_path), serialized as JSON here.
"""

from .queues import (MESSAGE_QUEUES, FileQueue, LogQueue, MessageQueue,
                     SqliteQueue, attach_to_filer, event_of,
                     load_configuration)

__all__ = ["MessageQueue", "LogQueue", "FileQueue", "SqliteQueue",
           "MESSAGE_QUEUES", "load_configuration", "attach_to_filer",
           "event_of"]
