"""Cloud/broker notification publishers: kafka, AWS SQS, GCP Pub/Sub.

Reference: weed/notification/kafka/kafka_queue.go (sarama async producer),
aws_sqs/aws_sqs_pub.go (SendMessage with the path in a message attribute),
google_pub_sub/google_pub_sub.go (topic ensure + publish).

The client libraries are not baked into this image, so each queue imports
its driver lazily at initialize() time and raises a clear error when
absent. Every initialize() accepts an injected `client` so the publishing
logic itself is exercised by the fake-driver contract tests
(tests/test_notification_brokers.py) even without the real broker.

Wire format: JSON bytes of the EventNotification dict (queues.event_of) —
the reference publishes the protobuf EventNotification; this framework's
RPC layer is proto-field-faithful JSON throughout (pb/messages.py).
"""

from __future__ import annotations

import json

from .queues import MessageQueue


def _encode(event: dict) -> bytes:
    return json.dumps(event).encode()


class KafkaQueue(MessageQueue):
    """kafka_queue.go: topic publisher keyed by the entry path."""

    name = "kafka"

    def __init__(self) -> None:
        self._producer = None
        self.topic = ""

    def initialize(self, config: dict, client=None) -> None:
        """config: {"hosts": [...], "topic": "seaweedfs_filer"}."""
        self.topic = config.get("topic", "seaweedfs_filer")
        if client is None:
            try:
                from kafka import KafkaProducer  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "notification queue 'kafka' requires the kafka-python "
                    "client, which is not available in this environment"
                ) from e
            client = KafkaProducer(bootstrap_servers=config["hosts"])
        self._producer = client

    def send_message(self, key: str, event: dict) -> None:
        if self._producer is None:
            raise RuntimeError("kafka queue not initialized")
        # sarama's AsyncProducer semantics: hand off to the client's
        # internal buffering; errors surface via flush/close
        self._producer.send(self.topic, key=key.encode(),
                            value=_encode(event))

    def close(self) -> None:
        if self._producer is not None:
            self._producer.flush()
            self._producer.close()


class SqsQueue(MessageQueue):
    """aws_sqs_pub.go: SendMessage with the key in a message attribute."""

    name = "aws_sqs"

    def __init__(self) -> None:
        self._client = None
        self.queue_url = ""

    def initialize(self, config: dict, client=None) -> None:
        """config: {"region": ..., "sqs_queue_name": ...} (+ standard AWS
        credential discovery, like the reference's aws_access_key_id
        fallback chain)."""
        if client is None:
            try:
                import boto3  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "notification queue 'aws_sqs' requires boto3, which "
                    "is not available in this environment") from e
            client = boto3.client("sqs", region_name=config.get("region"))
        self._client = client
        name = config["sqs_queue_name"]
        try:
            self.queue_url = client.get_queue_url(
                QueueName=name)["QueueUrl"]
        except Exception:
            # queueUrl lookup failing -> create (aws_sqs_pub.go:63-77)
            self.queue_url = client.create_queue(
                QueueName=name)["QueueUrl"]

    def send_message(self, key: str, event: dict) -> None:
        if self._client is None:
            raise RuntimeError("aws_sqs queue not initialized")
        self._client.send_message(
            QueueUrl=self.queue_url,
            MessageBody=_encode(event).decode(),
            MessageAttributes={
                "key": {"DataType": "String", "StringValue": key}})


class GooglePubSubQueue(MessageQueue):
    """google_pub_sub.go: ensure topic exists, publish keyed messages."""

    name = "google_pub_sub"

    def __init__(self) -> None:
        self._publisher = None
        self._topic_path = ""

    def initialize(self, config: dict, client=None) -> None:
        """config: {"project_id": ..., "topic": ...}."""
        topic = config.get("topic", "seaweedfs_filer_topic")
        if client is None:
            try:
                from google.cloud import pubsub_v1  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "notification queue 'google_pub_sub' requires "
                    "google-cloud-pubsub, which is not available in this "
                    "environment") from e
            client = pubsub_v1.PublisherClient()
        self._publisher = client
        self._topic_path = client.topic_path(config["project_id"], topic)
        # ensure-topic (google_pub_sub.go:53-63)
        try:
            client.get_topic(topic=self._topic_path)
        except Exception:
            client.create_topic(name=self._topic_path)

    def send_message(self, key: str, event: dict) -> None:
        if self._publisher is None:
            raise RuntimeError("google_pub_sub queue not initialized")
        self._publisher.publish(self._topic_path, _encode(event), key=key)
