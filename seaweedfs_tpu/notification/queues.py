"""Message queue implementations + registry.

Reference: weed/notification/configuration.go:10-58. The durable local
queues (file/sqlite) double as the subscription inputs the reference gets
from kafka offsets (replication/sub/notification_kafka.go keeps a
progress file of the last-consumed offset — same model here).
"""

from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
import time

logger = logging.getLogger("seaweedfs_tpu.notification")


def event_of(old, new, delete_chunks: bool = True) -> dict:
    """Build an EventNotification dict (pb/filer.proto EventNotification)
    from filer Entry objects."""
    return {
        "old_entry": old.to_dict() if old is not None else None,
        "new_entry": new.to_dict() if new is not None else None,
        "delete_chunks": delete_chunks,
        "new_parent_path": (new.dir_path if new is not None else ""),
        "ts_ns": time.time_ns(),
    }


class MessageQueue:
    """notification.MessageQueue (configuration.go:10-16)."""

    name = "base"

    def initialize(self, config: dict) -> None:
        raise NotImplementedError

    def send_message(self, key: str, event: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LogQueue(MessageQueue):
    """Log-only publisher (the reference's glog fallback)."""

    name = "log"

    def initialize(self, config: dict) -> None:
        pass

    def send_message(self, key: str, event: dict) -> None:
        logger.info("notify %s: %s", key, json.dumps(event)[:512])


class FileQueue(MessageQueue):
    """Append-only JSONL event log on local disk.

    Durable pub/sub for single-host deployments and tests; consumers track
    their byte offset the way the kafka input tracks partition offsets in
    a progress file (sub/notification_kafka.go:88-140).
    """

    name = "file"

    def __init__(self, path: str | None = None):
        if path:
            self.initialize({"path": path})

    def initialize(self, config: dict) -> None:
        self.path = config["path"]
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)

    def send_message(self, key: str, event: dict) -> None:
        line = json.dumps({"key": key, "event": event}) + "\n"
        with self._lock, open(self.path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())

    # -- consumer side --

    def read_from(self, offset: int = 0,
                  limit: int = 1 << 30) -> tuple[list[dict], int]:
        """Return (messages, new_offset) starting at byte `offset`."""
        out: list[dict] = []
        if not os.path.exists(self.path):
            return out, offset
        with open(self.path, "rb") as f:
            f.seek(offset)
            for raw in f:
                if len(out) >= limit:
                    break
                offset += len(raw)
                raw = raw.strip()
                if raw:
                    out.append(json.loads(raw))
        return out, offset


class SqliteQueue(MessageQueue):
    """Sqlite-backed queue with monotonically increasing ids; consumers
    poll `after` their last-seen id (the SQS/pubsub-analog with explicit
    acknowledgement by offset)."""

    name = "sqlite"

    def __init__(self, path: str | None = None):
        if path:
            self.initialize({"path": path})

    def initialize(self, config: dict) -> None:
        self.path = config["path"]
        self._lock = threading.Lock()
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS events ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " key TEXT, event TEXT, ts REAL)")
        self._db.commit()

    def send_message(self, key: str, event: dict) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO events (key, event, ts) VALUES (?,?,?)",
                (key, json.dumps(event), time.time()))
            self._db.commit()

    def read_after(self, after_id: int = 0,
                   limit: int = 1024) -> list[tuple[int, dict]]:
        with self._lock:
            rows = self._db.execute(
                "SELECT id, key, event FROM events WHERE id > ? "
                "ORDER BY id LIMIT ?", (after_id, limit)).fetchall()
        return [(i, {"key": k, "event": json.loads(e)}) for i, k, e in rows]

    def close(self) -> None:
        self._db.close()


def _broker_queues() -> "list[MessageQueue]":
    from .brokers import GooglePubSubQueue, KafkaQueue, SqsQueue
    return [KafkaQueue(), SqsQueue(), GooglePubSubQueue()]


MESSAGE_QUEUES: list[MessageQueue] = [
    LogQueue(), FileQueue(), SqliteQueue(), *_broker_queues(),
]


def queue_from_spec(spec: str) -> MessageQueue:
    """Build a local queue from a `log | file:<path> | sqlite:<path>`
    CLI/shell spec (the -notify flag style shared by the filer command
    and fs.meta.notify)."""
    if spec == "log":
        return LogQueue()
    kind, _, path = spec.partition(":")
    if kind == "file" and path:
        return FileQueue(path)
    if kind == "sqlite" and path:
        return SqliteQueue(path)
    raise ValueError(f"bad notify spec {spec!r}; "
                     f"use log | file:<path> | sqlite:<path>")


def load_configuration(config: dict | None) -> MessageQueue | None:
    """Pick the single enabled queue ([notification.<name>] enabled=true),
    mirroring configuration.go:24-58 incl. the exactly-one check."""
    if not config:
        return None
    enabled = [q for q in MESSAGE_QUEUES
               if config.get(q.name, {}).get("enabled")]
    if not enabled:
        return None
    if len(enabled) > 1:
        raise ValueError(
            "notification queue enabled for more than one broker: "
            + ", ".join(q.name for q in enabled))
    queue = enabled[0]
    queue.initialize(config[queue.name])
    return queue


def attach_to_filer(filer, queue: MessageQueue) -> None:
    """Wire Filer meta-change listeners to the queue
    (filer2/filer_notify.go:9-31 NotifyUpdateEvent)."""

    def on_change(old, new) -> None:
        key = (new or old).full_path
        queue.send_message(key, event_of(old, new))

    filer.listeners.append(on_change)
