"""ops subpackage."""
