"""CRC32-Castagnoli on device as GF(2) matrix algebra.

Reference: the reference computes CRC32C on every needle write/read via
klauspost/crc32's SSE4.2/CLMUL path (weed/storage/needle/crc.go:11-25,
go.mod:40). A byte-serial CRC loop is the worst possible TPU program —
but CRC is LINEAR over GF(2): with zero-init, crc_state(M) = B·bits(M)
for a fixed 0/1 matrix B, and the 0xFFFFFFFF init folds in as one
affine constant. That turns a whole batch of checksums into integer
matmuls (int8 × int8 → int32 on the MXU) followed by `& 1`:

  stage 1   bits(B, K, L*8) @ BlockMat(L*8, 32)  -> per-block states
  stage 2   Y(B, K*32)      @ PowMat(K*32, 32)   -> whole-message state

BlockMat folds a byte's table contribution through the remaining
zero-byte advances inside its block; PowMat folds each block's state
through the remaining blocks (powers of the L-byte advance operator).
Both are precomputed on host per (n, L) and cached — they depend only
on the message length, not the data.

This is the SURVEY §2b item-2 surface: checksums ride along with
device-resident stripe data (e.g. verifying reconstructed needles or
scrubbing shards) instead of a host pass per buffer.
"""

from __future__ import annotations

import functools

import numpy as np

_POLY = 0x82F63B78  # reflected Castagnoli

_T = np.zeros(256, np.uint32)
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_POLY ^ (_c >> 1)) if (_c & 1) else (_c >> 1)
    _T[_i] = _c


def _advance(states: np.ndarray) -> np.ndarray:
    """One zero-byte advance A8 applied to u32-encoded GF(2) states."""
    return (states >> np.uint32(8)) ^ _T[states & np.uint32(0xFF)]


@functools.lru_cache(maxsize=16)
def _matrices(n: int, block: int) -> tuple[np.ndarray, np.ndarray, int]:
    """(BlockMat (block*8, 32) int8, PowMat (K*32, 32) int8, affine u32)
    for messages of exactly n bytes split into K = n/block blocks."""
    assert n % block == 0 and n > 0
    k_blocks = n // block
    # columns for byte j, bit b inside ONE block: A8^(block-1-j)(T[1<<b])
    base = _T[np.left_shift(1, np.arange(8))].astype(np.uint32)  # (8,)
    cols = np.zeros((block, 8), np.uint32)
    cur = base.copy()
    for j in range(block - 1, -1, -1):
        cols[j] = cur
        cur = _advance(cur)
    # BlockMat bits: (block*8, 32)
    bm = ((cols.reshape(block * 8, 1)
           >> np.arange(32, dtype=np.uint32)) & 1).astype(np.int8)
    # block-advance operator C = A8^block as 32 u32 columns
    c_cols = np.left_shift(np.uint32(1), np.arange(32, dtype=np.uint32))
    for _ in range(block):
        c_cols = _advance(c_cols)

    def apply_c(v: np.ndarray) -> np.ndarray:
        out = np.zeros_like(v)
        for b in range(32):
            out ^= np.where((v >> np.uint32(b)) & 1, c_cols[b],
                            np.uint32(0))
        return out

    # PowMat: block m's state passes through C^(K-1-m); build backwards
    pw = np.zeros((k_blocks, 32), np.uint32)
    cur = np.left_shift(np.uint32(1), np.arange(32, dtype=np.uint32))
    for m in range(k_blocks - 1, -1, -1):
        pw[m] = cur
        cur = apply_c(cur)
    pm = ((pw.reshape(k_blocks * 32, 1)
           >> np.arange(32, dtype=np.uint32)) & 1).astype(np.int8)
    # affine part: A8^n(0xFFFFFFFF). After the loop above `cur` holds the
    # columns of C^K = A8^n, and all-ones init means XOR of every column.
    aff = np.bitwise_xor.reduce(cur)
    return bm, pm, int(aff)


def _pick_block(n: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


def crc32c_batch(data, block: int | None = None):
    """CRC32C of every row of `data` ((B, n) uint8, device or host) as a
    (B,) uint32 jax array. Bit-exact with util.crc32c.crc32c."""
    import jax
    import jax.numpy as jnp

    data = jnp.asarray(data, jnp.uint8)
    b_msgs, n = data.shape
    blk = block or _pick_block(n)
    bm, pm, aff = _matrices(n, blk)
    k_blocks = n // blk

    @jax.jit
    def run(d):
        # unpack bits LSB-first: (B, n) -> (B, n*8) int8 in {0,1}
        bits = ((d[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1)
        bits = bits.reshape(b_msgs, k_blocks, blk * 8).astype(jnp.int8)
        y = jax.lax.dot_general(
            bits, jnp.asarray(bm),
            (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32) & 1      # (B, K, 32)
        y = y.reshape(b_msgs, k_blocks * 32).astype(jnp.int8)
        s = jax.lax.dot_general(
            y, jnp.asarray(pm),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32) & 1      # (B, 32)
        state = jnp.sum(
            s.astype(jnp.uint32) << jnp.arange(32, dtype=jnp.uint32),
            axis=-1, dtype=jnp.uint32)
        return state ^ jnp.uint32(aff) ^ jnp.uint32(0xFFFFFFFF)

    return run(data)
