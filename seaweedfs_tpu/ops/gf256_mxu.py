"""GF(256) shard transform as an MXU matmul (XLA-level).

Alternative to the VPU bitplane kernel (gf256_pallas.py). Over GF(2) the
whole transform is a binary matmul: expand the (rows, k) GF(256)
coefficient matrix to its (8*rows, 8*k) bit-matrix (gf.gf2_matrix), unpack
shard bytes to bitplanes, multiply on the systolic array, and reduce mod 2.

Why it can win: the VPU path costs ~8*k*(2+2*rows) ALU ops per u32 word —
compute-bound far below HBM speed; the MXU formulation moves the O(k*rows)
work onto the 128x128 systolic array whose int8/bf16 throughput is ~two
orders of magnitude higher, leaving only O(k+rows) elementwise unpack/pack
on the VPU. Bitplanes stay in u32-word space, so no transposes: bit
position p*8+j of a word only ever mixes with bit positions p*8+b of the
same byte slot p, giving out_plane = A @ in_plane (mod 2) with planes laid
out elementwise over the (wm, 128) word grid.

bench.py measures this against the Pallas path on the real chip and
reports the faster one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ec import gf


@functools.lru_cache(maxsize=256)
def _plane_matrix(coeff_key: bytes, rows: int, k: int) -> np.ndarray:
    """(rows*32, k*32) f32 0/1 matrix mapping k shards x 32 input
    bitplanes to rows x 32 output planes.

    Plane index layout: shard-major, then u32 bit position (p*8 + j) for
    byte slot p in 0..3, bit j. Byte slots never mix, so the matrix is
    block-diagonal over p with the (8*rows, 8*k) GF(2) matrix's entries
    shuffled to plane order."""
    coeff = np.frombuffer(coeff_key, dtype=np.uint8).reshape(rows, k)
    g2 = gf.gf2_matrix(coeff)  # (8*rows, 8*k): [r*8+b, i*8+j]
    out = np.zeros((rows * 32, k * 32), np.float32)
    for p in range(4):  # byte slot within the u32 word
        for r in range(rows):
            for b in range(8):
                for i in range(k):
                    for j in range(8):
                        out[r * 32 + p * 8 + b, i * 32 + p * 8 + j] = \
                            g2[r * 8 + b, i * 8 + j]
    return out


# wm rows per matmul chunk. The bitplane unpack is a 64x expansion in
# bf16 (32 planes x 2 bytes per input u32 word), so an unchunked 64 MB
# shard stream would materialize a 21 GB operand (> 16 GB HBM — the
# round-3 OOM). 2048 word-rows bound the live operand to ~170 MB while
# keeping each dot_general large enough to saturate the systolic array.
_CHUNK_WM = 2048


def _mxu_block(a: np.ndarray, x: jax.Array) -> jax.Array:
    """x: (k, cm, 128) u32 -> (rows, cm, 128) u32 via one GF(2) matmul."""
    k, cm, lanes = x.shape
    rows = a.shape[0] // 32
    # unpack the 32 bitplanes of every word: (k, 32, cm, 128) — XLA fuses
    # the shifts into the matmul operand production
    shifts = jnp.arange(32, dtype=jnp.uint32)
    planes = ((x[:, None] >> shifts[None, :, None, None])
              & jnp.uint32(1))
    # (k*32, cm*128) bf16 operand; 0/1 values are exact in bf16 and the
    # f32-accumulated sums (<= 8k) are exact integers
    full = planes.reshape(k * 32, -1).astype(jnp.bfloat16)
    s = jax.lax.dot_general(
        jnp.asarray(a, jnp.bfloat16), full,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # (rows*32, cm*128)
    obits = s.astype(jnp.uint32) & jnp.uint32(1)
    # pack 32 planes back into u32 words per output row
    obits = obits.reshape(rows, 32, cm, lanes)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return (obits * weights[None, :, None, None]).sum(
        axis=1, dtype=jnp.uint32)


def mxu_words_transform(coeff: np.ndarray, words: list[jax.Array],
                        chunk_wm: int = _CHUNK_WM) -> list[jax.Array]:
    """Same contract as gf256_pallas.gf256_words_transform: k arrays of
    (wm, 128) uint32 -> rows arrays alike, out = coeff (x) in over
    GF(256). Streams the bitplane expansion through bounded chunks."""
    coeff = np.ascontiguousarray(coeff, dtype=np.uint8)
    rows, k = coeff.shape
    assert len(words) == k
    a = _plane_matrix(coeff.tobytes(), rows, k)  # (rows*32, k*32)

    x = jnp.stack(words, axis=0)  # (k, wm, 128) u32
    wm = x.shape[1]
    if wm <= chunk_wm:
        packed = _mxu_block(a, x)
        return [packed[r] for r in range(rows)]
    pad = (-wm) % chunk_wm
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    nchunks = x.shape[1] // chunk_wm
    xc = jnp.moveaxis(
        x.reshape(k, nchunks, chunk_wm, 128), 1, 0)  # (nchunks, k, cm, 128)
    out = jax.lax.map(lambda c: _mxu_block(a, c), xc)
    packed = jnp.moveaxis(out, 0, 1).reshape(rows, -1, 128)
    if pad:
        packed = packed[:, :wm]
    return [packed[r] for r in range(rows)]
