"""Pallas TPU kernel for the GF(256) shard transform.

This is the TPU replacement for klauspost/reedsolomon's PSHUFB/AVX2
galois-multiply assembly (reference dep of ec_encoder.go:192). One kernel
evaluates out = C (x) data over GF(256), where C is a small (rows, k)
coefficient matrix (4x10 for RS(10,4) encode; (r,10) for reconstruct) and
data is k shard byte-streams.

Math: gf_mul(c, x) = XOR_j bit_j(x) * gf_mul(c, 1<<j), so the transform is
AND/XOR over the 8 bitplanes of each input byte with 8 precomputed constant
bytes per coefficient. To quadruple VPU lane utilisation the byte streams
are viewed as uint32 words and all bitplane ops are done byte-wise inside
the word:

    bits  = (x >> j) & 0x01010101          # bit j of each of the 4 bytes
    acc  ^= bits * K                       # K < 256: no cross-byte carries

Layout: each shard is its own (wm, 128) uint32 array — the natural TPU tile
for 32-bit data, with zero padding waste (a single (k, n) array would pad
the k=10 sublane dim to the tile quantum and transpose-copy in HBM). Byte
streams convert to this shape with a free numpy view on host. The kernel
reads each input block exactly once from HBM and the grid pipeline
double-buffers HBM->VMEM DMAs automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (memory spaces)

from ..ec import gf

_LANES = 128
# Sublane rows of 128 u32 words per block per shard:
# bm=256 -> 128 KiB/shard-block, 1.25 MiB input block for k=10.
_DEFAULT_BM = 256
_BLOCK_BYTES = _LANES * 4


def _accumulate_bitplanes(consts: np.ndarray, read_shard) -> list:
    """Shared kernel body: XOR-accumulate the 8 bitplanes of each of k
    input blocks against the per-row constants. read_shard(i) -> the
    i-th shard's (bm, 128) uint32 block; returns one accumulator per
    output row (None where a row's coefficients are all zero)."""
    rows, k, _ = consts.shape
    accs = [None] * rows
    for i in range(k):
        xi = read_shard(i)
        for j in range(8):
            ks = [int(consts[r, i, j]) for r in range(rows)]
            if not any(ks):
                continue
            bits = jax.lax.shift_right_logical(
                xi, jnp.uint32(j)) & jnp.uint32(0x01010101)
            for r in range(rows):
                if ks[r] == 0:
                    continue
                term = bits * jnp.uint32(ks[r])
                accs[r] = term if accs[r] is None else accs[r] ^ term
    return accs


def _make_kernel(consts: np.ndarray):
    """consts: (rows, k, 8) uint8 bitplane constants (host)."""
    rows, k, _ = consts.shape

    def kernel(*refs):
        ins, outs = refs[:k], refs[k:]
        accs = _accumulate_bitplanes(consts, lambda i: ins[i][...])
        for r in range(rows):
            outs[r][...] = (accs[r] if accs[r] is not None
                            else jnp.zeros_like(ins[0][...]))

    return kernel


@functools.lru_cache(maxsize=256)
def _build_call(consts_key: bytes, rows: int, k: int, wm: int, bm: int,
                interpret: bool):
    consts = np.frombuffer(consts_key, dtype=np.uint8).reshape(rows, k, 8)
    spec = pl.BlockSpec((bm, _LANES), lambda i: (i, 0))
    return pl.pallas_call(
        _make_kernel(consts),
        out_shape=[jax.ShapeDtypeStruct((wm, _LANES), jnp.uint32)] * rows,
        grid=(wm // bm,),
        in_specs=[spec] * k,
        out_specs=[spec] * rows,
        interpret=interpret,
    )


def gf256_words_transform(consts: np.ndarray, words: list[jax.Array],
                          block_bm: int = _DEFAULT_BM,
                          interpret: bool | None = None) -> list[jax.Array]:
    """Fast path: k device arrays of (wm, 128) uint32 -> rows arrays alike.

    wm must be a multiple of block_bm (callers pad the byte streams to the
    block quantum: block_bm * 512 bytes). This is the shape the EC pipeline
    and bench feed directly (numpy `.view(np.uint32).reshape(-1, 128)` of a
    shard byte buffer is free).
    """
    consts = np.ascontiguousarray(consts, dtype=np.uint8)
    rows, k, _ = consts.shape
    assert len(words) == k, (len(words), k)
    wm = words[0].shape[0]
    bm = min(block_bm, wm)
    assert wm % bm == 0, (wm, bm)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    call = _build_call(consts.tobytes(), rows, k, wm, bm, interpret)
    return call(*words)


def _make_stacked_kernel(consts: np.ndarray):
    """Single-ref variant: in (1, k, bm, 128), out (1, rows, bm, 128).
    Same bitplane math as _make_kernel, but volumes/shards live in one
    contiguous array — the layout the mesh-batched rack encode uses, so
    no per-shard slicing copies are needed."""
    rows, k, _ = consts.shape

    def kernel(in_ref, out_ref):
        accs = _accumulate_bitplanes(consts, lambda i: in_ref[0, i])
        for r in range(rows):
            out_ref[0, r] = (accs[r] if accs[r] is not None
                             else jnp.zeros_like(in_ref[0, 0]))

    return kernel


@functools.lru_cache(maxsize=256)
def _build_stacked_call(consts_key: bytes, rows: int, k: int, b: int,
                        wm: int, bm: int, interpret: bool):
    consts = np.frombuffer(consts_key, dtype=np.uint8).reshape(rows, k, 8)
    return pl.pallas_call(
        _make_stacked_kernel(consts),
        out_shape=jax.ShapeDtypeStruct((b, rows, wm, _LANES), jnp.uint32),
        grid=(b, wm // bm),
        in_specs=[pl.BlockSpec((1, k, bm, _LANES), lambda v, i: (v, 0, i, 0))],
        out_specs=pl.BlockSpec((1, rows, bm, _LANES),
                               lambda v, i: (v, 0, i, 0)),
        interpret=interpret,
    )


def gf256_stacked_transform(consts: np.ndarray, x: jax.Array,
                            block_bm: int = _DEFAULT_BM,
                            interpret: bool | None = None) -> jax.Array:
    """Batched fast path: (B, k, wm, 128) uint32 -> (B, rows, wm, 128).

    One pallas_call carries a whole batch of volumes (grid = B x wm/bm);
    the rack-encode mesh path calls this per-device inside shard_map.
    """
    consts = np.ascontiguousarray(consts, dtype=np.uint8)
    rows, k, _ = consts.shape
    b, kk, wm, lanes = x.shape
    assert kk == k and lanes == _LANES, (x.shape, consts.shape)
    # bm must divide wm exactly; fall back to the gcd for word counts
    # that aren't multiples of the preferred block (mesh callers only
    # guarantee 512-byte alignment per device)
    bm = min(block_bm, wm)
    if wm % bm:
        import math
        bm = math.gcd(wm, bm)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    call = _build_stacked_call(consts.tobytes(), rows, k, b, wm, bm,
                               interpret)
    return call(x)


def u8_to_words(d: jax.Array) -> jax.Array:
    """(..., n) uint8 -> (..., n//512, 128) uint32 on device (free bitcast;
    n must be a multiple of 512). Matches bytes_to_words' host layout."""
    *batch, n = d.shape
    assert n % (_BLOCK_BYTES) == 0, n
    w = jax.lax.bitcast_convert_type(
        d.reshape(*batch, n // 4, 4), jnp.uint32)
    return w.reshape(*batch, n // _BLOCK_BYTES, _LANES)


def words_to_u8(w: jax.Array) -> jax.Array:
    """(..., wm, 128) uint32 -> (..., wm*512) uint8 on device."""
    *batch, wm, lanes = w.shape
    b8 = jax.lax.bitcast_convert_type(w, jnp.uint8)  # (..., wm, 128, 4)
    return b8.reshape(*batch, wm * lanes * 4)


def bytes_to_words(buf: np.ndarray | bytes, block_bm: int = _DEFAULT_BM
                   ) -> np.ndarray:
    """Host-side free-ish view of a byte stream as (wm, 128) uint32,
    zero-padded to the block quantum."""
    arr = np.frombuffer(buf, dtype=np.uint8) if isinstance(
        buf, (bytes, bytearray, memoryview)) else np.asarray(buf, np.uint8)
    quantum = block_bm * _BLOCK_BYTES
    padded = -(-arr.size // quantum) * quantum
    if padded != arr.size:
        out = np.zeros(padded, np.uint8)
        out[:arr.size] = arr
        arr = out
    return arr.view(np.uint32).reshape(-1, _LANES)


def words_to_bytes(words: np.ndarray, n: int) -> np.ndarray:
    """Inverse of bytes_to_words, truncated to n bytes."""
    return np.asarray(words).reshape(-1).view(np.uint8)[:n]


def gf256_matmul_pallas(consts: np.ndarray, data: jax.Array,
                        block_bm: int = _DEFAULT_BM,
                        interpret: bool | None = None) -> jax.Array:
    """Generic API: out[..., r, :] = XOR_i gf_mul(coeff[r,i], data[..., i, :]).

    consts: (rows, k, 8) uint8 from gf.bitplane_constants (host constant).
    data: (..., k, n) uint8 jax array. Convenience wrapper around the words
    fast path — converts layout on device, so prefer gf256_words_transform
    for bulk streaming work.
    """
    consts = np.ascontiguousarray(consts, dtype=np.uint8)
    rows, k, _ = consts.shape
    data = jnp.asarray(data, jnp.uint8)
    *batch, kk, n = data.shape
    assert kk == k, (data.shape, consts.shape)

    flat = jnp.moveaxis(data, -2, 0).reshape(k, -1) if batch else data
    total = flat.shape[1]
    if total == 0:
        return jnp.zeros(tuple(batch) + (rows, n), jnp.uint8)
    bm = min(block_bm, max(8, -(-total // _BLOCK_BYTES)))
    quantum = bm * _BLOCK_BYTES
    padded = -(-total // quantum) * quantum
    if padded != total:
        flat = jnp.pad(flat, ((0, 0), (0, padded - total)))

    words = [
        jax.lax.bitcast_convert_type(
            flat[i].reshape(padded // 4, 4), jnp.uint32).reshape(-1, _LANES)
        for i in range(k)
    ]
    outs = gf256_words_transform(consts, words, bm, interpret)
    out = jnp.stack([
        jax.lax.bitcast_convert_type(o.reshape(-1), jnp.uint8
                                     ).reshape(-1)[:total]
        for o in outs
    ])
    if batch:
        out = jnp.moveaxis(out.reshape([rows] + batch + [n]), 0, -2)
    return out
