"""parallel subpackage."""
