"""Device-mesh parallelism for batched EC work.

The reference's parallelism is goroutine fan-out over gRPC (SURVEY 2c):
parallel shard copies to 14 servers (command_ec_encode.go:201-238), parallel
>=10-shard gathers for reconstruct (store_ec.go:329-362). TPU-native, the
same shapes become a 2D jax.sharding.Mesh:

  axis "vol"   — data parallel over independent volumes (a rack encode:
                 64 x 30GB volumes at once)
  axis "shard" — the 14 EC shards of each volume, sharded over ICI;
                 rebuild all_gathers the present shards across this axis

Encode is per-byte-column independent, so it runs with zero collectives;
rebuild uses one all_gather over the shard axis — that is the ICI
re-expression of the reference's goroutine+WaitGroup shard gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ec import gf
from ..ec.encoder_jax import _apply_bitplanes


def make_mesh(devices=None, vol_axis: int | None = None) -> Mesh:
    """2D ("vol", "shard") mesh over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if vol_axis is None:
        # widest vol axis such that shard axis fits 14's divisors (1, 2, 7, 14)
        for shard in (2, 7, 14, 1):
            if n % shard == 0 and shard <= n:
                vol_axis = n // shard
                break
    shard_axis = n // vol_axis
    dev_array = np.array(devices[:vol_axis * shard_axis]).reshape(
        vol_axis, shard_axis)
    return Mesh(dev_array, ("vol", "shard"))


@functools.lru_cache(maxsize=32)
def _encode_consts() -> np.ndarray:
    return gf.bitplane_constants(gf.parity_matrix())


def batched_encode(mesh: Mesh, data: jax.Array) -> jax.Array:
    """data: (V, k, n) uint8 -> (V, k+m, n) full shard sets.

    V is sharded over "vol", the byte columns n over "shard" (a
    sequence-parallel-style split: encode is columnwise independent, so both
    axes shard with no collectives). A ragged V (rack encode: more volumes
    than devices with an uneven tail) is zero-padded to the vol-axis
    quantum — padding encodes to garbage that is sliced off, costing one
    extra volume-row per launch at worst.
    """
    consts = _encode_consts()

    @jax.jit
    def step(d):
        parity = _apply_bitplanes(consts, d)
        return jnp.concatenate([d, parity], axis=-2)

    data = jnp.asarray(data, jnp.uint8)  # no-op for device-resident input
    v = data.shape[0]
    vol_dim = mesh.devices.shape[0]
    padded = -(-v // vol_dim) * vol_dim
    if padded != v:
        data = jnp.pad(data, ((0, padded - v), (0, 0), (0, 0)))
    spec = NamedSharding(mesh, P("vol", None, "shard"))
    data = jax.device_put(data, spec)
    out = step(data)
    return out[:v] if padded != v else out


def batched_rebuild(mesh: Mesh, present_rows: list[int],
                    shards: jax.Array, want_rows: list[int]) -> jax.Array:
    """shards: (V, k, n) — the k present shard rows of V volumes, laid out
    across the "shard" mesh axis; rebuild want_rows for every volume.

    The shard axis is all-gathered over ICI inside shard_map (the
    goroutine-gather of store_ec.go:329-362 become one XLA collective),
    then each device computes the missing rows for its slice of volumes.
    """
    coeff = gf.shard_rows(list(want_rows), list(present_rows))
    consts = gf.bitplane_constants(coeff)
    k = len(present_rows)

    def local(d):  # d: (V/vol, k/shard, n)
        gathered = jax.lax.all_gather(d, "shard", axis=1, tiled=True)
        return _apply_bitplanes(consts, gathered)

    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=P("vol", "shard", None),
                       out_specs=P("vol", None, None),
                       check_vma=False)
    spec = NamedSharding(mesh, P("vol", "shard", None))
    shards = jax.device_put(jnp.asarray(shards, jnp.uint8), spec)
    assert shards.shape[-2] == k, (shards.shape, k)
    return jax.jit(fn)(shards)


def full_cycle_step(mesh: Mesh, data: jax.Array,
                    lost_rows: tuple[int, ...] = (0, 11, 12, 13)):
    """One complete distributed EC "training step" analog: encode a batch
    of volumes, then rebuild a worst-case loss pattern from the survivors,
    and return (encoded, rebuilt) for verification."""
    encoded = batched_encode(mesh, data)
    present = [i for i in range(gf.TOTAL_SHARDS) if i not in lost_rows]
    use = present[:gf.DATA_SHARDS]
    survivors = encoded[:, jnp.array(use), :]
    rebuilt = batched_rebuild(mesh, use, survivors, list(lost_rows))
    return encoded, rebuilt
