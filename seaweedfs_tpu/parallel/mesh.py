"""Device-mesh parallelism for batched EC work.

The reference's parallelism is goroutine fan-out over gRPC (SURVEY 2c):
parallel shard copies to 14 servers (command_ec_encode.go:201-238), parallel
>=10-shard gathers for reconstruct (store_ec.go:329-362). TPU-native, the
same shapes become a 2D jax.sharding.Mesh:

  axis "vol"   — data parallel over independent volumes (a rack encode:
                 64 x 30GB volumes at once)
  axis "shard" — byte-column (sequence-parallel-style) split for encode,
                 and the 14 EC shards of each volume for rebuild; rebuild
                 all_gathers the present shards across this axis over ICI

Encode is per-byte-column independent, so it runs with zero collectives;
rebuild uses one all_gather over the shard axis — that is the ICI
re-expression of the reference's goroutine+WaitGroup shard gather.

Compute inside each device's shard_map block goes through the SAME Pallas
bitplane kernel as the single-stream path (ops/gf256_pallas.py,
gf256_stacked_transform): XLA cannot partition an opaque pallas_call over a
sharded array, so the mesh decomposition is explicit and each device
launches the kernel on its local (V/vol, k, n/shard) block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # older jax spells the replication-check kwarg check_rep
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)

from ..ec import gf
from ..ops.gf256_pallas import (gf256_stacked_transform, u8_to_words,
                                words_to_u8)

# byte-column quantum per device: one (1, 128) u32 lane row
_COL_QUANTUM = 512


def make_mesh(devices=None, vol_axis: int | None = None) -> Mesh:
    """2D ("vol", "shard") mesh over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if vol_axis is None:
        # widest vol axis such that shard axis fits 14's divisors (1, 2, 7, 14)
        for shard in (2, 7, 14, 1):
            if n % shard == 0 and shard <= n:
                vol_axis = n // shard
                break
    shard_axis = n // vol_axis
    dev_array = np.array(devices[:vol_axis * shard_axis]).reshape(
        vol_axis, shard_axis)
    return Mesh(dev_array, ("vol", "shard"))


@functools.lru_cache(maxsize=32)
def _encode_consts() -> np.ndarray:
    return gf.bitplane_constants(gf.parity_matrix())


def _pad_axis(x: jax.Array, axis: int, quantum: int) -> jax.Array:
    size = x.shape[axis]
    padded = -(-size // quantum) * quantum
    if padded == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, padded - size)
    return jnp.pad(x, pads)


def _stacked_apply(consts: np.ndarray, d: jax.Array) -> jax.Array:
    """(V, k, n) uint8 -> (V, rows, n) uint8 through the Pallas kernel;
    n must be a multiple of 512 (callers pad)."""
    return words_to_u8(gf256_stacked_transform(consts, u8_to_words(d)))


@functools.lru_cache(maxsize=64)
def _encode_fn(mesh: Mesh):
    """jit(shard_map) for batched encode, cached per mesh so repeated
    calls (the bench loop, a rack encode feeding batches) don't
    re-trace."""
    consts = _encode_consts()

    def local(d):  # d: (V/vol, k, n/shard)
        parity = _stacked_apply(consts, d)
        return jnp.concatenate([d, parity], axis=-2)

    return jax.jit(_shard_map(local, mesh=mesh,
                                 in_specs=P("vol", None, "shard"),
                                 out_specs=P("vol", None, "shard"),
                                 check_vma=False))


def batched_encode(mesh: Mesh, data: jax.Array) -> jax.Array:
    """data: (V, k, n) uint8 -> (V, k+m, n) full shard sets.

    V is sharded over "vol", the byte columns n over "shard" (encode is
    columnwise independent, so both axes shard with no collectives). A
    ragged V (rack encode: more volumes than devices with an uneven tail)
    is zero-padded to the vol-axis quantum — padding encodes to garbage
    that is sliced off, costing one extra volume-row per launch at worst;
    n pads to the 512-byte-per-device column quantum the kernel tiles on.
    """
    data = jnp.asarray(data, jnp.uint8)  # no-op for device-resident input
    v, k, n = data.shape
    vol_dim, shard_dim = mesh.devices.shape
    data = _pad_axis(data, 0, vol_dim)
    data = _pad_axis(data, 2, _COL_QUANTUM * shard_dim)
    spec = NamedSharding(mesh, P("vol", None, "shard"))
    out = _encode_fn(mesh)(jax.device_put(data, spec))
    if (out.shape[0], out.shape[2]) != (v, n):
        out = out[:v, :, :n]
    return out


@functools.lru_cache(maxsize=256)
def _rebuild_fn(mesh: Mesh, present_rows: tuple, want_rows: tuple):
    coeff = gf.shard_rows(list(want_rows), list(present_rows))
    consts = gf.bitplane_constants(coeff)
    shard_dim = mesh.devices.shape[1]

    def local(d):  # d: (V/vol, k/shard, n_pad)
        gathered = jax.lax.all_gather(d, "shard", axis=1, tiled=True)
        # rebuild only this device's column slice; out_specs reassemble
        cols = gathered.shape[2] // shard_dim
        me = jax.lax.axis_index("shard")
        mine = jax.lax.dynamic_slice_in_dim(gathered, me * cols, cols,
                                            axis=2)
        return _stacked_apply(consts, mine)

    return jax.jit(_shard_map(local, mesh=mesh,
                                 in_specs=P("vol", "shard", None),
                                 out_specs=P("vol", None, "shard"),
                                 check_vma=False))


def batched_rebuild(mesh: Mesh, present_rows: list[int],
                    shards: jax.Array, want_rows: list[int]) -> jax.Array:
    """shards: (V, k, n) — the k present shard rows of V volumes, laid out
    across the "shard" mesh axis; rebuild want_rows for every volume.

    The shard axis is all-gathered over ICI inside shard_map (the
    goroutine-gather of store_ec.go:329-362 become one XLA collective);
    each device then rebuilds its own slice of byte columns, so the
    compute — the same Pallas kernel as encode — also scales over the
    shard axis instead of being replicated.
    """
    k = len(present_rows)
    vol_dim, shard_dim = mesh.devices.shape
    shards = jnp.asarray(shards, jnp.uint8)
    v, kk, n = shards.shape
    assert kk == k, (shards.shape, k)
    shards = _pad_axis(shards, 0, vol_dim)
    shards = _pad_axis(shards, 2, _COL_QUANTUM * shard_dim)
    spec = NamedSharding(mesh, P("vol", "shard", None))
    fn = _rebuild_fn(mesh, tuple(present_rows), tuple(want_rows))
    out = fn(jax.device_put(shards, spec))
    return out[:v, :, :n]


@functools.lru_cache(maxsize=8)
def _verify_fn(mesh: Mesh):
    consts = _encode_consts()

    def local(d):  # d: (V/vol, k+m, n/shard)
        par = _stacked_apply(consts, d[:, :gf.DATA_SHARDS, :])
        diff = (par ^ d[:, gf.DATA_SHARDS:, :]) != 0
        bad = jnp.sum(diff, axis=(1, 2), dtype=jnp.int32)  # (V/vol,)
        # each device scrubbed its own byte columns; one ICI psum makes
        # the per-volume verdict global
        return jax.lax.psum(bad, "shard")

    return jax.jit(_shard_map(local, mesh=mesh,
                                 in_specs=P("vol", None, "shard"),
                                 out_specs=P("vol"),
                                 check_vma=False))


def batched_verify(mesh: Mesh, shards: jax.Array) -> jax.Array:
    """Distributed parity scrub: shards (V, k+m, n) -> (V,) int32
    mismatched-parity-byte counts (0 = stripe consistent).

    The mesh analog of `EcVolume.verify_parity`/`ec.verify`: every
    device recomputes parity for its column slice through the same
    stacked Pallas kernel as encode, and a single `psum` over the shard
    axis aggregates the verdicts — integrity checking as one collective
    instead of the reference's host CRC loop (needle/crc.go)."""
    shards = jnp.asarray(shards, jnp.uint8)
    v, rows, n = shards.shape
    assert rows == gf.TOTAL_SHARDS, shards.shape
    vol_dim, shard_dim = mesh.devices.shape
    # zero padding is parity-consistent (parity of zeros is zeros), so
    # padded volumes/columns contribute zero mismatches
    shards = _pad_axis(shards, 0, vol_dim)
    shards = _pad_axis(shards, 2, _COL_QUANTUM * shard_dim)
    spec = NamedSharding(mesh, P("vol", None, "shard"))
    out = _verify_fn(mesh)(jax.device_put(shards, spec))
    return out[:v]


def full_cycle_step(mesh: Mesh, data: jax.Array,
                    lost_rows: tuple[int, ...] = (0, 11, 12, 13)):
    """One complete distributed EC "training step" analog: encode a batch
    of volumes, then rebuild a worst-case loss pattern from the survivors,
    and return (encoded, rebuilt) for verification."""
    encoded = batched_encode(mesh, data)
    present = [i for i in range(gf.TOTAL_SHARDS) if i not in lost_rows]
    use = present[:gf.DATA_SHARDS]
    survivors = encoded[:, jnp.array(use), :]
    rebuilt = batched_rebuild(mesh, use, survivors, list(lost_rows))
    return encoded, rebuilt
