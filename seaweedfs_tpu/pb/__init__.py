"""pb subpackage."""
