"""Wire messages for inter-server RPC.

The reference defines these in protobuf (pb/master.proto, volume_server.proto,
filer.proto) over gRPC; this build carries the same fields as JSON over the
asyncio HTTP mesh (bulk shard/needle bytes travel as raw HTTP bodies, not
JSON). Field names follow the protos so the mapping stays auditable.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field


@dataclass
class VolumeInformationMessage:
    """master.proto VolumeInformationMessage (heartbeat volume entry)."""
    id: int
    size: int = 0
    collection: str = ""
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: int = 0
    version: int = 3
    ttl: int = 0
    compact_revision: int = 0
    # .dat lives on a tier backend (volume_tier.py): the autopilot
    # must never re-plan tier_seal for an already-remote volume
    remote: bool = False

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeInformationMessage":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})


@dataclass
class VolumeEcShardInformationMessage:
    """master.proto VolumeEcShardInformationMessage: vid + shard bitmask."""
    id: int
    collection: str = ""
    ec_index_bits: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeEcShardInformationMessage":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})


@dataclass
class Heartbeat:
    """master.proto Heartbeat: full + delta volume/EC-shard sync."""
    ip: str = ""
    port: int = 0
    public_url: str = ""
    max_volume_count: int = 0
    max_file_key: int = 0
    data_center: str = ""
    rack: str = ""
    volumes: list[VolumeInformationMessage] = field(default_factory=list)
    new_volumes: list[VolumeInformationMessage] = field(default_factory=list)
    deleted_volumes: list[VolumeInformationMessage] = field(default_factory=list)
    ec_shards: list[VolumeEcShardInformationMessage] = field(default_factory=list)
    new_ec_shards: list[VolumeEcShardInformationMessage] = field(default_factory=list)
    deleted_ec_shards: list[VolumeEcShardInformationMessage] = field(default_factory=list)
    has_no_volumes: bool = False
    has_no_ec_shards: bool = False

    def to_dict(self) -> dict:
        return {
            "ip": self.ip, "port": self.port, "public_url": self.public_url,
            "max_volume_count": self.max_volume_count,
            "max_file_key": self.max_file_key,
            "data_center": self.data_center, "rack": self.rack,
            "volumes": [v.to_dict() for v in self.volumes],
            "new_volumes": [v.to_dict() for v in self.new_volumes],
            "deleted_volumes": [v.to_dict() for v in self.deleted_volumes],
            "ec_shards": [s.to_dict() for s in self.ec_shards],
            "new_ec_shards": [s.to_dict() for s in self.new_ec_shards],
            "deleted_ec_shards": [s.to_dict() for s in self.deleted_ec_shards],
            "has_no_volumes": self.has_no_volumes,
            "has_no_ec_shards": self.has_no_ec_shards,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Heartbeat":
        h = cls(**{k: d.get(k, cls.__dataclass_fields__[k].default)
                   for k in ("ip", "port", "public_url", "max_volume_count",
                             "max_file_key", "data_center", "rack",
                             "has_no_volumes", "has_no_ec_shards")})
        h.volumes = [VolumeInformationMessage.from_dict(x)
                     for x in d.get("volumes", [])]
        h.new_volumes = [VolumeInformationMessage.from_dict(x)
                         for x in d.get("new_volumes", [])]
        h.deleted_volumes = [VolumeInformationMessage.from_dict(x)
                             for x in d.get("deleted_volumes", [])]
        h.ec_shards = [VolumeEcShardInformationMessage.from_dict(x)
                       for x in d.get("ec_shards", [])]
        h.new_ec_shards = [VolumeEcShardInformationMessage.from_dict(x)
                           for x in d.get("new_ec_shards", [])]
        h.deleted_ec_shards = [VolumeEcShardInformationMessage.from_dict(x)
                               for x in d.get("deleted_ec_shards", [])]
        return h


def shard_bits_add(bits: int, shard_id: int) -> int:
    """ShardBits bitmask ops (ec_volume_info.go:61-113)."""
    return bits | (1 << shard_id)


def shard_bits_remove(bits: int, shard_id: int) -> int:
    return bits & ~(1 << shard_id)


def shard_bits_list(bits: int) -> list[int]:
    return [i for i in range(32) if bits & (1 << i)]


def shard_bits_count(bits: int) -> int:
    return bin(bits).count("1")
