"""Multi-tenant QoS: admission, shedding, and bandwidth arbitration.

`admission.py` holds the per-tenant classification / rate-limit /
weighted-fair-queue / shed machinery; `arbiter.py` the cluster-wide
background-vs-foreground bandwidth arbiter. This module owns the
per-process singletons the servers consult, the tenant-identity
extraction shared by the filer/WebDAV tiers, and the merged
`/debug/qos` surface.
"""

from __future__ import annotations

import base64
import contextvars
import json

from .admission import (DEFAULT, AdmissionController, Decision,  # noqa: F401
                        RateBucket, TenantClass, WFQ,
                        parse_tenant_flag, parse_tenant_flags)
from .arbiter import BandwidthArbiter, GrantBucket  # noqa: F401

_admission: "AdmissionController | None" = None
_arbiter: "BandwidthArbiter | None" = None

# the requesting tenant's CLASS, set by entry middlewares for the
# request's task context — downstream consumers (util/resilience.py
# retry-budget keying) read it without plumbing a parameter through
# every hop
_class_var: contextvars.ContextVar = contextvars.ContextVar(
    "qos_class", default="")


def init_admission(tenant_specs, *, lag_shed_ms: float = 0.0,
                   wait_shed_ms: float = 0.0,
                   inflight_limit: int = 256,
                   queue_deadline_s: float = 2.0) -> AdmissionController:
    """Parse -qos.tenant flags and install the process admission
    plane. Raises ValueError on malformed specs (boot-time refusal)."""
    global _admission
    _admission = AdmissionController(
        parse_tenant_flags(tenant_specs), lag_shed_ms=lag_shed_ms,
        wait_shed_ms=wait_shed_ms, inflight_limit=inflight_limit,
        queue_deadline_s=queue_deadline_s)
    return _admission


def admission() -> "AdmissionController | None":
    return _admission


def init_arbiter(budget_mbps: float = 0.0,
                 floor: float = 0.25) -> BandwidthArbiter:
    global _arbiter
    _arbiter = BandwidthArbiter(budget_mbps=budget_mbps, floor=floor)
    return _arbiter


def arbiter() -> "BandwidthArbiter | None":
    return _arbiter


def note_foreground(nbytes: int) -> None:
    """Hot-path foreground byte accounting (server/wire.py,
    server/fasthttp.py). Cheap no-op until an arbiter exists."""
    if _arbiter is not None and nbytes:
        _arbiter.note_foreground(nbytes)


def set_current_class(cls: str):
    """Tag the running context with the admitted tenant class;
    returns the reset token."""
    return _class_var.set(cls)


def current_class() -> str:
    return _class_var.get()


def reset(state=None) -> None:
    """Test hook: drop the singletons (and optionally restore)."""
    global _admission, _arbiter
    if state is None:
        _admission = _arbiter = None
    else:
        _admission, _arbiter = state


# ---------------------------------------------------------------------------
# tenant identity extraction (filer / WebDAV tiers; S3 uses the
# SigV4-verified access key directly)

def tenant_from_headers(headers) -> str:
    """Best-effort identity for classification: the SigV4 credential
    access key when the request is AWS-signed, else the JWT `sub`
    claim (payload-decoded only — this keys CLASSIFICATION and rate
    limits, not authorization, which stays with the verifying
    tiers), else empty (-> default class)."""
    auth = headers.get("Authorization", "") if headers else ""
    if auth.startswith("AWS4-HMAC-SHA256"):
        # ... Credential=AKID/20260101/region/s3/aws4_request, ...
        i = auth.find("Credential=")
        if i >= 0:
            cred = auth[i + len("Credential="):]
            return cred.split("/", 1)[0].split(",", 1)[0].strip()
    if auth.startswith("Bearer "):
        token = auth[7:]
        parts = token.split(".")
        if len(parts) == 3:
            try:
                pad = parts[1] + "=" * (-len(parts[1]) % 4)
                claims = json.loads(base64.urlsafe_b64decode(pad))
                sub = claims.get("sub", "")
                if isinstance(sub, str) and sub:
                    return sub
            except (ValueError, TypeError):
                pass
    return ""


# ---------------------------------------------------------------------------
# /debug/qos

def qos_dict() -> dict:
    """The process-local QoS surface (merged across -workers by
    merge_payloads, exactly like timeline/events)."""
    d: dict = {}
    if _admission is not None:
        d.update(_admission.to_dict())
    if _arbiter is not None:
        d["arbiter"] = _arbiter.to_dict()
    return {"qos": d}


def merge_payloads(payloads: "list[dict]") -> dict:
    """Fold several workers' /debug/qos payloads into one whole-host
    view: counters sum, shed level and probes take the worst worker,
    policy/config rows come from the first worker that has them."""
    merged: dict = {}
    tenants: dict = {}
    consumers: dict = {}
    grants: list = []
    for p in payloads:
        q = (p or {}).get("qos") or {}
        for label, row in (q.get("tenants") or {}).items():
            t = tenants.get(label)
            if t is None:
                tenants[label] = dict(row)
                continue
            for k in ("admitted", "throttled", "shed", "queued",
                      "queue_depth"):
                t[k] = t.get(k, 0) + row.get(k, 0)
            t["tokens"] = round(t.get("tokens", 0.0)
                                + row.get("tokens", 0.0), 3)
        for k in ("inflight", "inflight_limit", "queued"):
            if k in q:
                merged[k] = merged.get(k, 0) + q[k]
        if "shed_level" in q:
            merged["shed_level"] = max(merged.get("shed_level", 0),
                                       q["shed_level"])
        for k in ("ladder", "thresholds", "queue_deadline_s"):
            if k in q and k not in merged:
                merged[k] = q[k]
        if "probes" in q:
            cur = merged.setdefault("probes",
                                    {"lag_ms": 0.0, "wait_ms": 0.0})
            for k in ("lag_ms", "wait_ms"):
                cur[k] = max(cur[k], q["probes"].get(k, 0.0))
        a = q.get("arbiter")
        if a:
            arb = merged.setdefault(
                "arbiter", {"budget_mbps": 0.0, "floor": a.get("floor"),
                            "foreground_bps": 0.0})
            arb["budget_mbps"] = max(arb["budget_mbps"],
                                     a.get("budget_mbps", 0.0))
            arb["foreground_bps"] = round(
                arb["foreground_bps"] + a.get("foreground_bps", 0.0), 1)
            for kind, c in (a.get("consumers") or {}).items():
                m = consumers.setdefault(
                    kind, {"base_bps": 0, "rate_bps": 0,
                           "granted_bytes": 0, "yields": 0,
                           "slept_s": 0.0})
                for k in ("base_bps", "rate_bps", "granted_bytes",
                          "yields"):
                    m[k] += c.get(k, 0)
                m["slept_s"] = round(m["slept_s"]
                                     + c.get("slept_s", 0.0), 3)
            grants.extend(a.get("grants") or ())
    if tenants:
        merged["tenants"] = tenants
    if "arbiter" in merged:
        merged["arbiter"]["consumers"] = consumers
        grants.sort(key=lambda g: g.get("wall_ms", 0))
        merged["arbiter"]["grants"] = grants[-16:]
    return {"qos": merged, "workers": len(payloads)}


async def debug_handler(req):
    """GET /debug/qos — the single-process form (the -workers volume
    server merges siblings itself, server/volume_server.py)."""
    from aiohttp import web
    return web.json_response(qos_dict())
