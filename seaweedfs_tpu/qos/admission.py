"""Per-tenant admission: token-bucket rate limits, a weighted-fair
queue in front of the event loop, and priority-aware load shedding.

Tenancy model: every request entering an S3 / filer / WebDAV tier is
classified by its access key (SigV4 credential) or JWT identity into a
*tenant class* configured via repeatable `-qos.tenant
"key:weight:rps[:burst]"` flags; unknown identities fall into the
`default` class. The controller then applies, in order:

1. **overload shedding** — when the saturation probes
   (stats/saturation.py: event-loop lag, executor queue wait) cross
   the armed `-qos.shed.lagms` / `-qos.shed.waitms` thresholds, the
   lowest-weight classes are shed FIRST (503 + Retry-After), one
   ladder rung per `LEVEL_STEP_S`, with hysteresis on recovery. The
   highest-weight class is never overload-shed — its protection is
   the point of the ladder (it still rate-limits).
2. **per-tenant rate limit** — a non-sleeping token bucket per class;
   a drained bucket answers 429 with `Retry-After` computed from the
   bucket's own refill, never a guess.
3. **weighted-fair queueing** — when the process is at its in-flight
   limit, waiters park in a virtual-time WFQ (start-time fair
   queueing: backlogged classes are served in proportion to weight).
   A waiter that would exceed `queue_deadline_s` is shed with 503 —
   requests are never silently queued past a deadline.

Every throttle/shed decision lands in the metrics
(`SeaweedFS_qos_decisions_total`) and — rate-bounded per tenant — the
event journal (`tenant_shed`), so SLO evidence can correlate a paying
tenant's burn with the abuser being shed. The `qos.admit` failpoint
lets chaos force any decision path.

References: start-time fair queueing (Goyal et al.) for the virtual
clock; the priority discipline of arXiv:2306.10528 (foreground-
impacting work first) for the shed ladder.
"""

from __future__ import annotations

import asyncio
import heapq
import time

DEFAULT = "default"


class TenantClass:
    """One configured tenant: identity key == class name."""

    __slots__ = ("name", "weight", "rps", "burst")

    def __init__(self, name: str, weight: float, rps: float,
                 burst: float | None = None):
        self.name = name
        self.weight = weight
        self.rps = rps
        self.burst = burst if burst is not None else max(rps, 1.0)

    def to_dict(self) -> dict:
        return {"weight": self.weight, "rps": self.rps,
                "burst": self.burst}


def parse_tenant_flag(spec: str) -> TenantClass:
    """Parse one `-qos.tenant "key:weight:rps[:burst]"` value.

    Raises ValueError on malformed specs — cli init refuses them at
    boot (the slo.init discipline: a typo'd policy must not silently
    admit everything)."""
    parts = [p.strip() for p in spec.split(":")]
    if len(parts) not in (3, 4):
        raise ValueError(
            f"qos.tenant {spec!r}: want key:weight:rps[:burst]")
    key = parts[0]
    try:
        weight = float(parts[1])
        rps = float(parts[2])
        burst = float(parts[3]) if len(parts) == 4 else None
    except ValueError:
        raise ValueError(f"qos.tenant {spec!r}: non-numeric field")
    if not key:
        raise ValueError(f"qos.tenant {spec!r}: empty key")
    if weight <= 0:
        raise ValueError(f"qos.tenant {spec!r}: weight must be > 0")
    if rps < 0:
        raise ValueError(f"qos.tenant {spec!r}: rps must be >= 0")
    if burst is not None and burst <= 0:
        raise ValueError(f"qos.tenant {spec!r}: burst must be > 0")
    return TenantClass(key, weight, rps, burst)


def parse_tenant_flags(specs) -> "dict[str, TenantClass]":
    """All -qos.tenant flags -> {key: TenantClass}, with a `default`
    class (weight 1, unlimited rps) ensured for unknown identities."""
    out: dict[str, TenantClass] = {}
    for spec in specs or ():
        t = parse_tenant_flag(spec)
        if t.name in out:
            raise ValueError(f"qos.tenant {spec!r}: duplicate key")
        out[t.name] = t
    if DEFAULT not in out:
        out[DEFAULT] = TenantClass(DEFAULT, 1.0, 0.0, 1.0)
    return out


class RateBucket:
    """Non-sleeping token bucket for request admission.

    Unlike ec/scrub.TokenBucket (which paces by sleeping), admission
    must answer NOW: try_take() either debits and returns 0.0, or
    leaves the bucket untouched and returns the seconds until the
    deficit refills — exactly the honest `Retry-After` value.
    rate <= 0 disables the limit. Injectable clock for determinism."""

    __slots__ = ("rate", "burst", "_tokens", "_last", "_now")

    def __init__(self, rate: float, burst: float | None = None,
                 now=time.monotonic):
        self.rate = rate
        self.burst = burst if burst is not None else max(rate, 1.0)
        self._now = now
        self._tokens = self.burst
        self._last = now()

    def _refill(self) -> None:
        now = self._now()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0) -> float:
        """0.0 = admitted (n debited); > 0 = denied, retry after."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return 0.0
        return (n - self._tokens) / self.rate

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until n tokens are available (0.0 if now/unlimited)."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        if self._tokens >= n:
            return 0.0
        return (n - self._tokens) / self.rate

    @property
    def tokens(self) -> float:
        self._refill()
        return self._tokens


class WFQ:
    """Virtual-time weighted fair queue (start-time fair queueing).

    push() tags an item with a virtual finish time
    `vf = max(V, last_vf[tenant]) + cost / weight`; pop() serves the
    smallest vf and advances V to it. Backlogged tenants therefore
    receive service in proportion to their weights; an idle tenant
    re-enters at the current virtual clock (no banked credit). Ties
    break on arrival order — the whole structure is deterministic for
    identical push/pop sequences, which the property tests rely on."""

    def __init__(self, weights: "dict[str, float]"):
        self._w = dict(weights)
        self._v = 0.0
        self._last: dict[str, float] = {}
        self._heap: list = []
        self._seq = 0
        self._depth: dict[str, int] = {}

    def push(self, tenant: str, item, cost: float = 1.0) -> None:
        w = max(self._w.get(tenant, 1.0), 1e-9)
        vf = max(self._v, self._last.get(tenant, 0.0)) + cost / w
        self._last[tenant] = vf
        heapq.heappush(self._heap, (vf, self._seq, tenant, item))
        self._seq += 1
        self._depth[tenant] = self._depth.get(tenant, 0) + 1

    def pop(self):
        """(tenant, item) with the smallest virtual finish, or None."""
        if not self._heap:
            return None
        vf, _, tenant, item = heapq.heappop(self._heap)
        self._v = max(self._v, vf)
        d = self._depth.get(tenant, 1) - 1
        if d:
            self._depth[tenant] = d
        else:
            self._depth.pop(tenant, None)
        return tenant, item

    def __len__(self) -> int:
        return len(self._heap)

    def depth(self, tenant: str) -> int:
        return self._depth.get(tenant, 0)

    def depths(self) -> dict:
        return dict(self._depth)


class Decision:
    """Outcome of one admission attempt. `tenant` is the BOUNDED
    metric label for the raw identity (stats/metrics.BoundedLabelSet);
    `cls` is the tenant class that policy applied."""

    __slots__ = ("admitted", "status", "retry_after_s", "tenant", "cls",
                 "reason", "queued_s")

    def __init__(self, admitted: bool, status: int = 200,
                 retry_after_s: float = 0.0, tenant: str = "",
                 cls: str = DEFAULT, reason: str = "",
                 queued_s: float = 0.0):
        self.admitted = admitted
        self.status = status
        self.retry_after_s = retry_after_s
        self.tenant = tenant
        self.cls = cls
        self.reason = reason
        self.queued_s = queued_s


def _default_probe() -> "tuple[float, float]":
    """(event-loop lag ms, executor queue wait ms) — live values from
    the saturation probes."""
    from ..stats import saturation
    return (saturation.current_lag_s() * 1000.0,
            saturation.current_exec_wait_s() * 1000.0)


class AdmissionController:
    """The per-process admission plane one entry tier consults."""

    LEVEL_STEP_S = 0.5      # at most one shed-ladder rung per step
    RECOVER_FRAC = 0.7      # hysteresis: recover below 70% of threshold
    EVENT_INTERVAL_S = 1.0  # tenant_shed journal rows, per tenant

    def __init__(self, tenants: "dict[str, TenantClass]", *,
                 lag_shed_ms: float = 0.0, wait_shed_ms: float = 0.0,
                 inflight_limit: int = 256,
                 queue_deadline_s: float = 2.0,
                 now=time.monotonic, probe=None, label_cap: int = 32):
        from ..stats import metrics
        self.tenants = dict(tenants)
        if DEFAULT not in self.tenants:
            self.tenants[DEFAULT] = TenantClass(DEFAULT, 1.0, 0.0, 1.0)
        self.lag_shed_ms = lag_shed_ms
        self.wait_shed_ms = wait_shed_ms
        self.inflight_limit = inflight_limit
        self.queue_deadline_s = queue_deadline_s
        self._now = now
        self._probe = probe or _default_probe
        self._buckets = {n: RateBucket(t.rps, t.burst, now=now)
                         for n, t in self.tenants.items()}
        self._wfq = WFQ({n: t.weight for n, t in self.tenants.items()})
        self._inflight = 0
        # the shed ladder: distinct class weights ascending, top class
        # excluded — overload sheds the lowest classes first and never
        # the highest (that protection is the whole point)
        distinct = sorted({t.weight for t in self.tenants.values()})
        self._ladder = distinct[:-1]
        self._level = 0
        self._level_ts = -1e9
        self._labels = metrics.BoundedLabelSet(seed=self.tenants,
                                               cap=label_cap)
        self._counts: dict[str, dict] = {}
        self._ev_ts: dict[str, float] = {}

    # -- classification ------------------------------------------------

    def classify(self, key: str) -> TenantClass:
        return self.tenants.get(key) or self.tenants[DEFAULT]

    def label_of(self, key: str) -> str:
        return self._labels.get(key or "anonymous")

    # -- shed ladder ---------------------------------------------------

    def _severity(self) -> float:
        """>= 1.0 means a saturation probe crossed its armed
        threshold. 0.0 when no threshold is armed."""
        lag_ms, wait_ms = self._probe()
        s = 0.0
        if self.lag_shed_ms > 0:
            s = max(s, lag_ms / self.lag_shed_ms)
        if self.wait_shed_ms > 0:
            s = max(s, wait_ms / self.wait_shed_ms)
        return s

    def _update_level(self) -> None:
        if not self._ladder or (self.lag_shed_ms <= 0
                                and self.wait_shed_ms <= 0):
            return
        now = self._now()
        if now - self._level_ts < self.LEVEL_STEP_S:
            return
        s = self._severity()
        if s >= 1.0 and self._level < len(self._ladder):
            self._level += 1
            self._level_ts = now
        elif s < self.RECOVER_FRAC and self._level > 0:
            self._level -= 1
            self._level_ts = now

    def _overloaded(self, cls: TenantClass) -> bool:
        return (self._level > 0
                and cls.weight <= self._ladder[self._level - 1])

    # -- bookkeeping ---------------------------------------------------

    def _count(self, label: str) -> dict:
        c = self._counts.get(label)
        if c is None:
            c = self._counts[label] = {"admitted": 0, "throttled": 0,
                                       "shed": 0, "queued": 0}
        return c

    def _reject(self, label: str, cls: TenantClass, status: int,
                reason: str, tier: str, op: str,
                retry_after: float = 0.0) -> Decision:
        from ..stats import metrics
        from ..util import events
        if retry_after <= 0.0:
            # no per-tenant refill to anchor on: the honest floor is
            # one ladder evaluation period
            retry_after = self._buckets[cls.name].retry_after() \
                or 2 * self.LEVEL_STEP_S
        kind = "throttled" if status == 429 else "shed"
        self._count(label)[kind] += 1
        if metrics.HAVE_PROMETHEUS:
            metrics.QOS_DECISIONS.labels(
                label, "throttle" if status == 429 else "shed").inc()
        now = self._now()
        # journal rows are rate-bounded per tenant: an abuser at full
        # throttle must not flood the ring that holds its own evidence
        if now - self._ev_ts.get(label, -1e9) >= self.EVENT_INTERVAL_S:
            self._ev_ts[label] = now
            events.record("tenant_shed", tenant=label, cls=cls.name,
                          reason=reason, status=status, tier=tier,
                          op=op, retry_after_s=round(retry_after, 3))
        return Decision(False, status=status, retry_after_s=retry_after,
                        tenant=label, cls=cls.name, reason=reason)

    # -- the admission path --------------------------------------------

    async def acquire(self, tier: str, op: str, key: str) -> Decision:
        """Admit, throttle (429), queue, or shed (503) one request."""
        from ..stats import metrics
        from ..util import failpoints
        cls = self.classify(key or "")
        label = self.label_of(key)
        try:
            await failpoints.fail("qos.admit")
        except OSError as e:
            # whatever status the injected fault carries, the contract
            # to the client is an honest shed: 429/503 + Retry-After
            status = getattr(e, "status", 503) or 503
            if status not in (429, 503):
                status = 503
            return self._reject(label, cls, status, "failpoint",
                                tier, op)
        self._update_level()
        if self._overloaded(cls):
            return self._reject(label, cls, 503, "overload", tier, op)
        ra = self._buckets[cls.name].try_take()
        if ra > 0.0:
            return self._reject(label, cls, 429, "throttle", tier, op,
                                retry_after=ra)
        queued_s = 0.0
        if self._inflight >= self.inflight_limit:
            loop = asyncio.get_running_loop()
            fut = loop.create_future()
            self._wfq.push(cls.name, fut)
            self._count(label)["queued"] += 1
            if metrics.HAVE_PROMETHEUS:
                metrics.QOS_QUEUE_DEPTH.labels(cls.name).set(
                    self._wfq.depth(cls.name))
            t0 = self._now()
            try:
                # never silently queue past the deadline: a waiter
                # that can't be served in time is shed with an honest
                # Retry-After instead of adding invisible latency
                await asyncio.wait_for(fut, self.queue_deadline_s)
            except asyncio.TimeoutError:
                return self._reject(label, cls, 503, "queue_deadline",
                                    tier, op)
            finally:
                queued_s = self._now() - t0
                if metrics.HAVE_PROMETHEUS:
                    metrics.QOS_QUEUE_DEPTH.labels(cls.name).set(
                        self._wfq.depth(cls.name))
        self._inflight += 1
        self._count(label)["admitted"] += 1
        if metrics.HAVE_PROMETHEUS:
            metrics.QOS_DECISIONS.labels(label, "admit").inc()
        return Decision(True, tenant=label, cls=cls.name,
                        queued_s=queued_s)

    def release(self, dec: Decision) -> None:
        """Request finished: free the slot and wake the next waiter in
        weighted-fair order (skipping waiters that already timed out)."""
        from ..stats import metrics
        if not dec.admitted:
            return
        self._inflight = max(0, self._inflight - 1)
        while self._inflight < self.inflight_limit:
            nxt = self._wfq.pop()
            if nxt is None:
                return
            tenant, fut = nxt
            if metrics.HAVE_PROMETHEUS:
                metrics.QOS_QUEUE_DEPTH.labels(tenant).set(
                    self._wfq.depth(tenant))
            if fut.done():        # deadline-shed while queued
                continue
            fut.set_result(None)  # the waiter claims the freed slot
            return

    def observe(self, tier: str, op: str, dec: Decision,
                seconds: float) -> None:
        """Per-tenant latency attribution — the histogram per-tenant
        -slo objectives evaluate against."""
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.QOS_TENANT_REQUEST_TIME.labels(
                tier, op, dec.tenant).observe(seconds)

    # -- introspection (/debug/qos) ------------------------------------

    def to_dict(self) -> dict:
        lag_ms, wait_ms = self._probe()
        depths = self._wfq.depths()
        tenants = {}
        for label, counts in sorted(self._counts.items()):
            cls = self.classify(label)
            row = dict(counts)
            row.update(cls=cls.name, weight=cls.weight, rps=cls.rps,
                       burst=cls.burst,
                       tokens=round(self._buckets[cls.name].tokens, 3),
                       queue_depth=depths.get(cls.name, 0))
            tenants[label] = row
        for name, cls in self.tenants.items():
            if name not in tenants:
                tenants[name] = {
                    "admitted": 0, "throttled": 0, "shed": 0,
                    "queued": 0, "cls": name, "weight": cls.weight,
                    "rps": cls.rps, "burst": cls.burst,
                    "tokens": round(self._buckets[name].tokens, 3),
                    "queue_depth": depths.get(name, 0)}
        return {
            "tenants": tenants,
            "inflight": self._inflight,
            "inflight_limit": self.inflight_limit,
            "queued": len(self._wfq),
            "queue_deadline_s": self.queue_deadline_s,
            "shed_level": self._level,
            "ladder": self._ladder,
            "thresholds": {"lag_ms": self.lag_shed_ms,
                           "wait_ms": self.wait_shed_ms},
            "probes": {"lag_ms": round(lag_ms, 3),
                       "wait_ms": round(wait_ms, 3)},
        }
