"""Cluster-wide bandwidth arbiter: one budget for background repair.

The scrub (-scrub.mbps), autopilot (-autopilot.mbps) and rebalance
paths each pace themselves with a private token bucket — honest
ledgers, but nothing arbitrates them against FOREGROUND traffic on
the same wire (the Facebook warehouse study, arXiv:1309.0186, found
unbudgeted repair routinely eating a large fraction of cluster
network). The arbiter closes that loop with the priority discipline
of arXiv:2306.10528: foreground-impacting work first, background
yields — but never below a starvation-proof floor, so repair always
converges.

Mechanics: the leader master publishes a byte budget (`-qos.mbps`)
through heartbeat responses; every node runs an arbiter that ADOPTS
its local background buckets (`adopt()` wraps a TokenBucket in a
drop-in facade routing `consume()` through `grant()`). Each grant
re-derives the consumer's allowed rate from live foreground pressure:

    pressure  p = min(1, foreground_bps / budget_bps)
    allowed_k   = base_k * max(floor, 1 - p)

so an idle cluster gives background its full configured rate, a
saturated one squeezes it to `floor * base` — never zero. Foreground
pressure is observed locally (server/wire.py notes every served byte)
and, on the master, aggregated from node heartbeat reports, making
the budget decision cluster-wide while each grant stays local.

Every reduction below base is a journalled `arbiter_yield`
(rate-bounded per consumer); every grant lands in a bounded ledger
(`/debug/qos`) and the `SeaweedFS_qos_arbiter_*` metrics — the
deterministic accounting the pacing-floor asserts check. The
`arbiter.grant` failpoint forces a grant to the starvation floor
(chaos: prove repair converges even when permanently squeezed).
"""

from __future__ import annotations

import time
from collections import deque

MiB = 1 << 20
FG_WINDOW_S = 2.0       # foreground rate observation window
NODE_REPORT_TTL_S = 15.0  # heartbeat-reported foreground freshness
LEDGER_ROWS = 64
EVENT_INTERVAL_S = 1.0


class GrantBucket:
    """Drop-in ec/scrub.TokenBucket facade: consume() routes through
    the arbiter so the owner (scrubber, autopilot executor) needs no
    code change — its bucket just became arbitrated."""

    def __init__(self, arbiter: "BandwidthArbiter", kind: str, inner):
        self._arbiter = arbiter
        self.kind = kind
        self.inner = inner

    @property
    def rate(self) -> float:
        return self.inner.rate

    @property
    def burst(self) -> float:
        return self.inner.burst

    async def consume(self, n: int) -> float:
        return await self._arbiter.grant(self.kind, n)


class BandwidthArbiter:
    """Per-process arbiter over adopted background token buckets."""

    def __init__(self, budget_mbps: float = 0.0, floor: float = 0.25,
                 now=time.monotonic):
        self.budget_bps = max(budget_mbps, 0.0) * MiB
        self.floor = min(max(floor, 0.0), 1.0)
        self._now = now
        # kind -> {"bucket": inner TokenBucket, "base": bytes/s,
        #          "granted": bytes, "yields": n, "slept_s": s}
        self._consumers: dict[str, dict] = {}
        self._fg: deque = deque()       # (t, nbytes) inside FG_WINDOW_S
        self._fg_bytes = 0.0
        self._nodes: dict[str, tuple] = {}  # node -> (t, bps)
        self.grants: deque = deque(maxlen=LEDGER_ROWS)
        self._ev_ts: dict[str, float] = {}

    # -- configuration -------------------------------------------------

    def adopt(self, kind: str, bucket) -> GrantBucket:
        """Register a background TokenBucket; its configured rate
        becomes the consumer's base entitlement."""
        self._consumers[kind] = {"bucket": bucket, "base": bucket.rate,
                                 "granted": 0, "yields": 0,
                                 "slept_s": 0.0}
        return GrantBucket(self, kind, bucket)

    def set_budget_mbps(self, mbps: float) -> None:
        """Leader-published budget pickup (heartbeat response)."""
        self.budget_bps = max(float(mbps), 0.0) * MiB

    # -- foreground pressure -------------------------------------------

    def note_foreground(self, nbytes: int) -> None:
        """One served foreground request's bytes (hot path: O(1)
        amortized — stale window entries retire on observation)."""
        now = self._now()
        self._fg.append((now, nbytes))
        self._fg_bytes += nbytes
        self._trim(now)

    def _trim(self, now: float) -> None:
        fg = self._fg
        cut = now - FG_WINDOW_S
        while fg and fg[0][0] < cut:
            self._fg_bytes -= fg.popleft()[1]

    def note_node_foreground(self, node: str, bps: float) -> None:
        """A heartbeat-reported foreground rate from one cluster node
        (master-side: makes the autopilot grant cluster-aware)."""
        self._nodes[node] = (self._now(), float(bps))

    def foreground_bps(self) -> float:
        """Local windowed foreground rate + fresh node reports."""
        now = self._now()
        self._trim(now)
        total = self._fg_bytes / FG_WINDOW_S
        for node, (t, bps) in list(self._nodes.items()):
            if now - t > NODE_REPORT_TTL_S:
                del self._nodes[node]
            else:
                total += bps
        return total

    # -- granting ------------------------------------------------------

    def rate_for(self, kind: str) -> float:
        """The rate this consumer is entitled to RIGHT NOW."""
        c = self._consumers.get(kind)
        if c is None:
            return 0.0
        base = c["base"]
        if base <= 0 or self.budget_bps <= 0:
            return base          # unpaced or arbiter disabled
        p = min(1.0, self.foreground_bps() / self.budget_bps)
        return base * max(self.floor, 1.0 - p)

    async def grant(self, kind: str, nbytes: int) -> float:
        """Admit nbytes of background work, pacing at the arbitrated
        rate; returns seconds slept (TokenBucket.consume contract)."""
        from ..stats import metrics
        from ..util import events, failpoints
        c = self._consumers.get(kind)
        if c is None:
            return 0.0
        rate = self.rate_for(kind)
        try:
            await failpoints.fail("arbiter.grant")
        except OSError:
            # chaos: squeeze this grant to the starvation floor — the
            # guarantee under test is that repair still converges
            if c["base"] > 0:
                rate = c["base"] * self.floor
        bucket = c["bucket"]
        yielded = c["base"] > 0 and rate < c["base"] - 1e-9
        if yielded:
            c["yields"] += 1
            if metrics.HAVE_PROMETHEUS:
                metrics.QOS_ARBITER_YIELDS.labels(kind).inc()
            now = self._now()
            if now - self._ev_ts.get(kind, -1e9) >= EVENT_INTERVAL_S:
                self._ev_ts[kind] = now
                events.record("arbiter_yield", kind=kind,
                              rate_bps=int(rate),
                              base_bps=int(c["base"]),
                              foreground_bps=int(self.foreground_bps()))
        bucket.rate = rate
        slept = await bucket.consume(nbytes)
        c["granted"] += nbytes
        c["slept_s"] += slept
        if metrics.HAVE_PROMETHEUS:
            metrics.QOS_ARBITER_GRANTED.labels(kind).inc(nbytes)
            metrics.QOS_ARBITER_RATE.labels(kind).set(round(rate, 1))
            metrics.QOS_FOREGROUND_BPS.set(
                round(self.foreground_bps(), 1))
        self.grants.append({"kind": kind, "bytes": int(nbytes),
                            "rate_bps": int(rate),
                            "slept_s": round(slept, 4),
                            "yielded": yielded,
                            "wall_ms": int(time.time() * 1000)})
        return slept

    # -- introspection (/debug/qos) ------------------------------------

    def to_dict(self) -> dict:
        return {
            "budget_mbps": round(self.budget_bps / MiB, 3),
            "floor": self.floor,
            "foreground_bps": round(self.foreground_bps(), 1),
            "consumers": {
                kind: {"base_bps": int(c["base"]),
                       "rate_bps": int(self.rate_for(kind)),
                       "granted_bytes": int(c["granted"]),
                       "yields": c["yields"],
                       "slept_s": round(c["slept_s"], 3)}
                for kind, c in self._consumers.items()},
            "grants": list(self.grants)[-16:],
        }
