from .json_query import Filter, query_json  # noqa: F401
