"""S3-Select-ish JSON query pushdown, evaluated inside the volume server.

Reference: weed/query/json/query_json.go:17-64 (`QueryJson`: scan JSON
documents/lines, apply a single field filter, project selected fields)
and weed/server/volume_grpc_query.go:12-67 (the `Query` RPC that streams
matching records for a list of file ids held by this volume server).

Documents are either one JSON object or JSONL (one object per line).
Filter operands follow the reference's comparison set: = != > >= < <=
plus `like` (substring match on the string form).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterator


OPERANDS = ("=", "!=", ">", ">=", "<", "<=", "like")


@dataclass
class Filter:
    field: str
    operand: str
    value: str

    @classmethod
    def from_dict(cls, d: dict | None) -> "Filter | None":
        if not d or not d.get("field"):
            return None
        return cls(field=d["field"], operand=d.get("operand", "="),
                   value=str(d.get("value", "")))


def get_path(doc: Any, path: str) -> Any:
    """Dotted-path lookup (gjson-style, minus wildcards): `a.b.0.c`."""
    cur = doc
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                return None
            cur = cur[part]
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return cur


def _compare(value: Any, op: str, operand: str) -> bool:
    if value is None:
        return False
    if op == "like":
        return operand in str(value)
    # numeric comparison when both sides parse as numbers, else string
    try:
        left: Any = float(value) if not isinstance(value, bool) else value
        right: Any = float(operand)
    except (TypeError, ValueError):
        left, right = str(value), operand
    if op == "=":
        return left == right
    if op == "!=":
        return left != right
    if op == ">":
        return left > right
    if op == ">=":
        return left >= right
    if op == "<":
        return left < right
    if op == "<=":
        return left <= right
    raise ValueError(f"unknown operand {op!r}")


def _documents(data: bytes) -> Iterator[Any]:
    text = data.decode("utf-8", errors="replace").strip()
    if not text:
        return
    # whole-body JSON first (object or array of objects)
    try:
        doc = json.loads(text)
        if isinstance(doc, list):
            yield from doc
        else:
            yield doc
        return
    except json.JSONDecodeError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            yield json.loads(line)
        except json.JSONDecodeError:
            continue


def query_json(data: bytes, flt: Filter | None,
               selections: list[str] | None) -> list[dict]:
    """Return projected records from `data` matching `flt`."""
    out: list[dict] = []
    for doc in _documents(data):
        if not isinstance(doc, (dict, list)):
            continue
        if flt is not None and not _compare(
                get_path(doc, flt.field), flt.operand, flt.value):
            continue
        if selections:
            out.append({s: get_path(doc, s) for s in selections})
        else:
            out.append(doc if isinstance(doc, dict) else {"value": doc})
    return out
