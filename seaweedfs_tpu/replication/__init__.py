"""Async cross-cluster replication (`weed filer.replicate` analog).

Reference: weed/replication/ — Replicator (replicator.go:34-82),
ReplicationSink contract (sink/replication_sink.go:10-17), sinks for
filer/S3/GCS/Azure/B2, FilerSource (source/filer_source.go), notification
inputs (sub/). Here the live sinks are filer (another cluster's filer
HTTP API), s3 (any S3-compatible endpoint, incl. our own gateway), and
local directory; cloud-SDK sinks are gated.
"""

from .replicator import Replicator  # noqa: F401
from .sink import SINKS, ReplicationSink  # noqa: F401
from .source import FilerSource  # noqa: F401
