"""Cloud replication sinks: GCS, Azure Blob, Backblaze B2.

Reference: weed/replication/sink/gcssink/gcs_sink.go,
azuresink/azure_sink.go, b2sink/b2_sink.go — whole-object materialization
of filer entries into a cloud bucket/container (directories are skipped;
updates are delete+rewrite or overwrite; deletes remove the object).

Drivers (google-cloud-storage / azure-storage-blob / b2sdk) are not in
this image, so they import lazily at start() and every sink accepts an
injected `client`, letting the fake-driver contract tests
(tests/test_cloud_sinks.py) execute the full create/update/delete logic.
"""

from __future__ import annotations

from ..filer.entry import Entry
from ..filer.stream import stream_chunk_views
from ..util import glog
from .sink import ReplicationSink


class _WholeObjectCloudSink(ReplicationSink):
    """Shared create/update/delete shape of the three cloud sinks: they
    differ only in the driver verbs (_put/_delete)."""

    def __init__(self, directory: str = "/", client=None):
        super().__init__()
        self.directory = directory.rstrip("/") or "/"
        self._client = client

    @property
    def sink_dir(self) -> str:
        return self.directory

    async def _object_bytes(self, entry: Entry) -> bytes:
        buf = bytearray()
        async for block in stream_chunk_views(
                self.source.client, entry.chunks, 0, entry.size):
            buf.extend(block)
        return bytes(buf)

    def _key(self, key: str) -> str:
        return key.lstrip("/")

    def _put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _delete(self, key: str) -> None:
        raise NotImplementedError

    async def create_entry(self, key: str, entry: Entry) -> None:
        if entry.is_directory:
            return  # object stores have no directories (gcs_sink.go:83)
        self._put(self._key(key), await self._object_bytes(entry))

    async def update_entry(self, key: str, old: Entry, new: Entry,
                           delete_chunks: bool) -> bool:
        # whole-object overwrite (the reference's sinks do delete +
        # re-create; an overwriting put is the same end state)
        await self.create_entry(key, new)
        return True

    async def delete_entry(self, key: str, is_directory: bool,
                           delete_chunks: bool) -> None:
        if is_directory:
            return
        self._delete(self._key(key))


class GcsSink(_WholeObjectCloudSink):
    """gcssink/gcs_sink.go — google-cloud-storage bucket writer."""

    name = "google_cloud_storage"

    def __init__(self, bucket: str, directory: str = "/", client=None):
        super().__init__(directory, client)
        self.bucket_name = bucket
        self._bucket = None

    async def start(self) -> None:
        if self._client is None:
            try:
                from google.cloud import storage  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "replication sink 'google_cloud_storage' requires "
                    "google-cloud-storage, which is not available in "
                    "this environment") from e
            self._client = storage.Client()
        self._bucket = self._client.bucket(self.bucket_name)

    def _put(self, key: str, data: bytes) -> None:
        self._bucket.blob(key).upload_from_string(data)

    def _delete(self, key: str) -> None:
        blob = self._bucket.blob(key)
        try:
            blob.delete()
        except Exception as e:
            # absent object: delete is idempotent (gcs_sink.go:66) —
            # but an auth/network fault must not hide behind that
            glog.V(1).infof("gcs delete %s swallowed: %r", key, e)


class AzureSink(_WholeObjectCloudSink):
    """azuresink/azure_sink.go — container blob writer."""

    name = "azure"

    def __init__(self, container: str, directory: str = "/",
                 account_name: str = "", account_key: str = "",
                 client=None):
        super().__init__(directory, client)
        self.container = container
        self.account_name = account_name
        self.account_key = account_key
        self._container = None

    async def start(self) -> None:
        if self._client is None:
            try:
                from azure.storage.blob import (  # type: ignore
                    BlobServiceClient)
            except ImportError as e:
                raise RuntimeError(
                    "replication sink 'azure' requires "
                    "azure-storage-blob, which is not available in this "
                    "environment") from e
            self._client = BlobServiceClient(
                account_url=(f"https://{self.account_name}"
                             f".blob.core.windows.net"),
                credential=self.account_key)
        self._container = self._client.get_container_client(self.container)

    def _put(self, key: str, data: bytes) -> None:
        self._container.upload_blob(key, data, overwrite=True)

    def _delete(self, key: str) -> None:
        try:
            self._container.delete_blob(key)
        except Exception as e:
            # idempotent delete (azure_sink.go:77-88), fault still logged
            glog.V(1).infof("azure delete %s swallowed: %r", key, e)


class B2Sink(_WholeObjectCloudSink):
    """b2sink/b2_sink.go — Backblaze B2 bucket writer via b2sdk."""

    name = "backblaze"

    def __init__(self, bucket: str, directory: str = "/",
                 key_id: str = "", application_key: str = "",
                 client=None):
        super().__init__(directory, client)
        self.bucket_name = bucket
        self.key_id = key_id
        self.application_key = application_key
        self._bucket = None

    async def start(self) -> None:
        if self._client is None:
            try:
                from b2sdk.v2 import (  # type: ignore
                    B2Api, InMemoryAccountInfo)
            except ImportError as e:
                raise RuntimeError(
                    "replication sink 'backblaze' requires b2sdk, which "
                    "is not available in this environment") from e
            api = B2Api(InMemoryAccountInfo())
            api.authorize_account("production", self.key_id,
                                  self.application_key)
            self._client = api
        self._bucket = self._client.get_bucket_by_name(self.bucket_name)

    def _put(self, key: str, data: bytes) -> None:
        self._bucket.upload_bytes(data, key)

    def _delete(self, key: str) -> None:
        try:
            for version, _ in self._bucket.list_file_versions(key):
                if version.file_name == key:
                    self._client.delete_file_version(version.id_,
                                                     version.file_name)
        except Exception as e:
            # idempotent delete across versions; log so a dead bucket
            # doesn't masquerade as "nothing to delete"
            glog.V(1).infof("b2 delete %s swallowed: %r", key, e)
