"""Event replay into a sink.

Reference: weed/replication/replicator.go:34-82 — key-prefix rewrite from
the source watch directory to the sink directory, then dispatch to
create/delete/update; an update against a missing target falls back to
delete+create.
"""

from __future__ import annotations

from ..filer.entry import Entry
from .sink import ReplicationSink
from .source import FilerSource


class Replicator:
    def __init__(self, source: FilerSource, sink: ReplicationSink):
        self.source = source
        self.sink = sink
        sink.set_source(source)

    def _rewrite_key(self, key: str) -> str | None:
        src_dir = self.source.dir
        if src_dir != "/" and not key.startswith(src_dir):
            return None  # outside the replicated subtree
        suffix = key[len(src_dir):] if src_dir != "/" else key
        base = self.sink.sink_dir.rstrip("/")
        return f"{base}/{suffix.lstrip('/')}"

    async def replicate(self, key: str, event: dict) -> bool:
        """Apply one EventNotification dict; returns False when skipped."""
        new_key = self._rewrite_key(key)
        if new_key is None:
            return False
        old = (Entry.from_dict(event["old_entry"])
               if event.get("old_entry") else None)
        new = (Entry.from_dict(event["new_entry"])
               if event.get("new_entry") else None)
        delete_chunks = bool(event.get("delete_chunks", True))

        if old is not None and new is None:
            await self.sink.delete_entry(new_key, old.is_directory,
                                         delete_chunks)
            return True
        if old is None and new is not None:
            await self.sink.create_entry(new_key, new)
            return True
        if old is None and new is None:
            return False

        if await self.sink.update_entry(new_key, old, new, delete_chunks):
            return True
        # missing on the target: delete (no-op) + create (replicator.go:60-67)
        await self.sink.delete_entry(new_key, old.is_directory, False)
        await self.sink.create_entry(new_key, new)
        return True
