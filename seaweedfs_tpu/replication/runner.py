"""The `filer.replicate` process loop.

Reference: weed/command/filer_replication.go:37-130 — subscribe to the
notification input, replay each event through the Replicator, persist
consumption progress (the kafka input's offset file,
sub/notification_kafka.go:88-140).
"""

from __future__ import annotations

import asyncio
import json
import os

from ..notification.queues import FileQueue, SqliteQueue
from ..util import tracing
from .replicator import Replicator


def _load_progress(path: str) -> int:
    try:
        with open(path) as f:
            return int(json.load(f)["offset"])
    except (OSError, ValueError, KeyError):
        return 0


def _save_progress(path: str, offset: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"offset": offset}, f)
    os.replace(tmp, path)


async def replicate_from_queue(queue, replicator: Replicator,
                               progress_path: str,
                               poll_interval: float = 0.5,
                               once: bool = False) -> int:
    """Drain the queue into the sink; returns events applied. With
    once=True, process the current backlog and return (for tests and
    batch catch-up runs).

    Inputs: FileQueue/SqliteQueue track consumption in progress_path;
    broker inputs (replication/sub.py: kafka/SQS/Pub-Sub) manage their
    own resume state (kafka offset file / broker acknowledgements) and
    are committed only AFTER the whole batch replicated — at-least-once,
    like the reference's success-callback ordering
    (filer_replication.go:37-130)."""
    from .sub import NotificationInput

    # progress-file reads/writes are disk I/O like the broker polls —
    # the loop here is shared with the source/sink aiohttp sessions
    offset = await tracing.run_in_executor(_load_progress, progress_path)
    applied = 0
    while True:
        tokens = None
        if isinstance(queue, FileQueue):
            msgs, offset = queue.read_from(offset)
            batch = [(m["key"], m["event"]) for m in msgs]
        elif isinstance(queue, SqliteQueue):
            rows = queue.read_after(offset)
            batch = [(m["key"], m["event"]) for _, m in rows]
            if rows:
                offset = rows[-1][0]
        elif isinstance(queue, NotificationInput):
            # broker polls are synchronous network I/O: keep them off
            # the event loop that the source/sink sessions share
            items = await tracing.run_in_executor(queue.receive_batch)
            batch = [(key, event) for key, event, _ in items]
            tokens = [tok for _, _, tok in items]
        else:
            raise ValueError(
                f"unsupported subscription input {type(queue).__name__}; "
                f"use a file/sqlite queue or a replication.sub input")
        for key, event in batch:
            await replicator.replicate(key, event)
            applied += 1
        if batch:
            if tokens is not None:
                await tracing.run_in_executor(queue.commit, tokens)
            else:
                await tracing.run_in_executor(
                    _save_progress, progress_path, offset)
        if once:
            return applied
        await asyncio.sleep(poll_interval)
