"""The `filer.replicate` process loop.

Reference: weed/command/filer_replication.go:37-130 — subscribe to the
notification input, replay each event through the Replicator, persist
consumption progress (the kafka input's offset file,
sub/notification_kafka.go:88-140).
"""

from __future__ import annotations

import asyncio
import json
import os

from ..notification.queues import FileQueue, SqliteQueue
from .replicator import Replicator


def _load_progress(path: str) -> int:
    try:
        with open(path) as f:
            return int(json.load(f)["offset"])
    except (OSError, ValueError, KeyError):
        return 0


def _save_progress(path: str, offset: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"offset": offset}, f)
    os.replace(tmp, path)


async def replicate_from_queue(queue, replicator: Replicator,
                               progress_path: str,
                               poll_interval: float = 0.5,
                               once: bool = False) -> int:
    """Drain the queue into the sink; returns events applied. With
    once=True, process the current backlog and return (for tests and
    batch catch-up runs)."""
    offset = _load_progress(progress_path)
    applied = 0
    while True:
        if isinstance(queue, FileQueue):
            msgs, offset = queue.read_from(offset)
            batch = msgs
        elif isinstance(queue, SqliteQueue):
            rows = queue.read_after(offset)
            batch = [m for _, m in rows]
            if rows:
                offset = rows[-1][0]
        else:
            raise ValueError(
                f"unsupported subscription input {type(queue).__name__}; "
                f"use a file or sqlite queue")
        for msg in batch:
            await replicator.replicate(msg["key"], msg["event"])
            applied += 1
        if batch:
            _save_progress(progress_path, offset)
        if once:
            return applied
        await asyncio.sleep(poll_interval)
