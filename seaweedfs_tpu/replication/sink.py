"""Replication sinks.

Reference: weed/replication/sink/replication_sink.go:10-17 (contract),
filersink/ (re-upload chunks into the target cluster, incremental
UpdateEntry via MinusChunks — filer_sink.go:136-209), s3sink/, plus
gated stubs where the reference uses cloud SDKs (gcssink, azuresink,
b2sink).
"""

from __future__ import annotations

from ..security import tls

import asyncio
import os

import aiohttp

from ..filer.entry import Entry
from ..filer.filechunks import FileChunk, minus_chunks
from ..filer.stream import stream_chunk_views
from ..util import failpoints, tracing
from ..util.client import WeedClient
from .source import FilerSource


class ReplicationSink:
    """sink.ReplicationSink contract."""

    name = "base"

    def __init__(self) -> None:
        self.source: FilerSource | None = None

    def set_source(self, source: FilerSource) -> None:
        self.source = source

    @property
    def sink_dir(self) -> str:
        return "/"

    async def start(self) -> None:
        pass

    async def close(self) -> None:
        pass

    async def create_entry(self, key: str, entry: Entry) -> None:
        raise NotImplementedError

    async def update_entry(self, key: str, old: Entry, new: Entry,
                           delete_chunks: bool) -> bool:
        """Returns True when an existing entry was updated in place."""
        raise NotImplementedError

    async def delete_entry(self, key: str, is_directory: bool,
                           delete_chunks: bool) -> None:
        raise NotImplementedError


class FilerSink(ReplicationSink):
    """Replicate into another cluster's filer.

    Chunk data is fetched from the source cluster and re-uploaded through
    the TARGET cluster's own master/volume tier, then the entry metadata
    is written via the target filer HTTP API (filersink/fetch_write.go:
    17-53 replicateChunks, filer_sink.go:84-133 CreateEntry).
    """

    name = "filer"

    def __init__(self, filer_url: str, target_master_url: str,
                 directory: str = "/", replication: str = "",
                 collection: str = "", ttl: str = ""):
        super().__init__()
        self.filer_url = filer_url
        self.master_url = target_master_url
        self.directory = directory.rstrip("/") or "/"
        self.replication = replication
        self.collection = collection
        self.ttl = ttl
        self._client: WeedClient | None = None
        self._http: aiohttp.ClientSession | None = None

    @property
    def sink_dir(self) -> str:
        return self.directory

    async def start(self) -> None:
        self._client = WeedClient(self.master_url)
        await self._client.__aenter__()
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=60))

    async def close(self) -> None:
        if self._client:
            await self._client.__aexit__()
        if self._http:
            await self._http.close()

    async def _replicate_chunks(
            self, chunks: list[FileChunk]) -> list[FileChunk]:
        from ..util import failpoints

        async def one(c: FileChunk) -> FileChunk:
            # chaos site: a flaky cross-cluster hop (FailpointError is
            # an OSError) surfaces to the runner, which retries the
            # whole entry — upload_data's own retry policy absorbs the
            # transient ones below it
            await failpoints.fail("replication.sink")
            data = await self.source.read_part(c.file_id)
            fid = await self._client.upload_data(
                data, collection=self.collection,
                replication=self.replication, ttl=self.ttl)
            return FileChunk(file_id=fid, offset=c.offset, size=c.size,
                             mtime=c.mtime, etag=c.etag)
        return list(await asyncio.gather(*(one(c) for c in chunks)))

    async def _find(self, key: str) -> Entry | None:
        await failpoints.fail("replication.sink.meta")
        async with self._http.get(
                tls.url(self.filer_url, "/__api__/lookup"),
                params={"path": key}) as resp:
            if resp.status != 200:
                return None
            body = await resp.json()
        return Entry(full_path=key, chunks=[
            FileChunk.from_dict(c) for c in body.get("chunks", [])])

    async def _write_meta(self, key: str, entry: Entry,
                          chunks: list[FileChunk]) -> None:
        payload = {
            "FullPath": key, "Mtime": entry.attr.mtime,
            "Crtime": entry.attr.crtime, "Mode": entry.attr.mode,
            "Uid": entry.attr.uid, "Gid": entry.attr.gid,
            "Mime": entry.attr.mime, "TtlSec": entry.attr.ttl_sec,
            "chunks": [c.to_dict() for c in chunks],
            "extended": entry.extended,
        }
        await failpoints.fail("replication.sink.meta")
        async with self._http.post(
                tls.url(self.filer_url, "/__api__/entry"),
                json=payload) as resp:
            if resp.status != 200:
                raise RuntimeError(
                    f"filer sink create_entry {key}: {await resp.text()}")

    async def create_entry(self, key: str, entry: Entry) -> None:
        if entry.is_directory:
            await self._write_meta(key, entry, [])
            return
        chunks = await self._replicate_chunks(entry.chunks)
        await self._write_meta(key, entry, chunks)

    async def update_entry(self, key: str, old: Entry, new: Entry,
                           delete_chunks: bool) -> bool:
        """Incremental diff (filer_sink.go:136-209): keep existing chunks
        minus deleted, append re-replicated new chunks."""
        existing = await self._find(key)
        if existing is None:
            return False
        deleted = minus_chunks(old.chunks, new.chunks)
        added = minus_chunks(new.chunks, old.chunks)
        kept = minus_chunks(existing.chunks, deleted)
        replicated = await self._replicate_chunks(added)
        await self._write_meta(key, new, kept + replicated)
        return True

    async def delete_entry(self, key: str, is_directory: bool,
                           delete_chunks: bool) -> None:
        params = {"recursive": "true"} if is_directory else {}
        await failpoints.fail("replication.sink.meta")
        async with self._http.delete(
                tls.url(self.filer_url, f"{key}"), params=params) as resp:
            if resp.status not in (200, 204, 404):
                raise RuntimeError(
                    f"filer sink delete {key}: {resp.status}")


class S3Sink(ReplicationSink):
    """Replicate objects into an S3-compatible endpoint (s3sink/) —
    whole-object PUTs assembled from the source chunk views."""

    name = "s3"

    def __init__(self, endpoint: str, bucket: str, directory: str = "/"):
        super().__init__()
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.directory = directory.rstrip("/") or "/"
        self._http: aiohttp.ClientSession | None = None

    @property
    def sink_dir(self) -> str:
        return self.directory

    async def start(self) -> None:
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=60))
        await failpoints.fail("replication.s3")
        async with self._http.put(
                f"{self.endpoint}/{self.bucket}") as resp:
            if resp.status not in (200, 409):
                raise RuntimeError(
                    f"s3 sink: cannot ensure bucket: {resp.status}")

    async def close(self) -> None:
        if self._http:
            await self._http.close()

    def _url(self, key: str) -> str:
        return f"{self.endpoint}/{self.bucket}/{key.lstrip('/')}"

    async def _object_bytes(self, entry: Entry) -> bytes:
        buf = bytearray()
        async for block in stream_chunk_views(
                self.source.client, entry.chunks, 0, entry.size):
            buf.extend(block)
        return bytes(buf)

    async def create_entry(self, key: str, entry: Entry) -> None:
        if entry.is_directory:
            return  # S3 has no directories
        data = await self._object_bytes(entry)
        await failpoints.fail("replication.s3")
        async with self._http.put(self._url(key), data=data) as resp:
            if resp.status != 200:
                raise RuntimeError(f"s3 sink put {key}: {resp.status}")

    async def update_entry(self, key: str, old: Entry, new: Entry,
                           delete_chunks: bool) -> bool:
        await self.create_entry(key, new)  # whole-object overwrite
        return True

    async def delete_entry(self, key: str, is_directory: bool,
                           delete_chunks: bool) -> None:
        if is_directory:
            return
        await failpoints.fail("replication.s3")
        async with self._http.delete(self._url(key)) as resp:
            if resp.status not in (200, 204, 404):
                raise RuntimeError(f"s3 sink delete {key}: {resp.status}")


class LocalDirSink(ReplicationSink):
    """Materialize the replicated tree on the local filesystem — the
    simplest end-to-end sink (plays the role of the GoCDK file backends)."""

    name = "local"

    def __init__(self, root: str):
        super().__init__()
        self.root = root

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.lstrip("/"))

    async def create_entry(self, key: str, entry: Entry) -> None:
        p = self._path(key)
        if entry.is_directory:
            await tracing.run_in_executor(
                lambda: os.makedirs(p, exist_ok=True))
            return
        buf = bytearray()
        async for block in stream_chunk_views(
                self.source.client, entry.chunks, 0, entry.size):
            buf.extend(block)
        data = bytes(buf)

        def write() -> None:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "wb") as f:
                f.write(data)

        # the runner's loop also carries the source/sink http sessions:
        # disk writes leave it
        await tracing.run_in_executor(write)

    async def update_entry(self, key: str, old: Entry, new: Entry,
                           delete_chunks: bool) -> bool:
        if not os.path.exists(self._path(key)):
            return False
        await self.create_entry(key, new)
        return True

    async def delete_entry(self, key: str, is_directory: bool,
                           delete_chunks: bool) -> None:
        p = self._path(key)
        if is_directory:
            import shutil
            await tracing.run_in_executor(
                lambda: shutil.rmtree(p, ignore_errors=True))
        elif os.path.exists(p):
            await tracing.run_in_executor(os.unlink, p)


def _sinks() -> dict:
    from .cloud_sinks import AzureSink, B2Sink, GcsSink
    return {
        "filer": FilerSink,
        "s3": S3Sink,
        "local": LocalDirSink,
        "google_cloud_storage": GcsSink,
        "azure": AzureSink,
        "backblaze": B2Sink,
    }


SINKS: dict[str, type] = _sinks()
