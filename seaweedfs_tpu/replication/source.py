"""Source-cluster data access for replication.

Reference: weed/replication/source/filer_source.go — resolve a chunk fid
to a volume-server URL on the SOURCE cluster and read its bytes.
"""

from __future__ import annotations

from ..util.client import WeedClient


class FilerSource:
    def __init__(self, master_url: str, directory: str = "/"):
        self.master_url = master_url
        self.dir = directory.rstrip("/") or "/"
        self._client: WeedClient | None = None

    async def __aenter__(self) -> "FilerSource":
        self._client = WeedClient(self.master_url)
        await self._client.__aenter__()
        return self

    async def __aexit__(self, *exc) -> None:
        if self._client:
            await self._client.__aexit__()

    @property
    def client(self) -> WeedClient:
        assert self._client is not None, "use 'async with FilerSource(...)'"
        return self._client

    async def read_part(self, fid: str, offset: int = 0,
                        size: int = -1) -> bytes:
        """source/filer_source.go ReadPart: fetch chunk bytes by fid."""
        return await self.client.read(fid, offset=offset, size=size)
