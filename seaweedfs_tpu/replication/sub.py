"""Broker subscription inputs for `filer.replicate`.

Reference: weed/replication/sub/ — NotificationInput implementations for
kafka (notification_kafka.go:88-140, with offset-file resume), AWS SQS
(notification_aws_sqs.go: receive + delete-on-success), and GCP Pub/Sub
(notification_google_pub_sub.go: subscription ensure + pull/ack).

Like the publishers (notification/brokers.py), the client libraries are
not baked into this image: each input imports its driver lazily at
initialize() time and accepts an injected `client`, so the consumption
logic — batching, offset resume, commit semantics — is exercised by
fake-driver contract tests (tests/test_replication_sub.py) without a
real broker.

Delivery contract: at-least-once. `receive_batch()` returns
[(key, event, token)]; the runner applies every event through the
Replicator and only then calls `commit(tokens)` — a crash between the
two replays the batch, mirroring the reference's success/failure
callback ordering (filer_replication.go:37-130).
"""

from __future__ import annotations

import json
import os


class NotificationInput:
    """Abstract subscription input (replication/sub/notifications.go)."""

    name = "abstract"

    def initialize(self, config: dict, client=None) -> None:
        raise NotImplementedError

    def receive_batch(self, max_messages: int = 64
                      ) -> list[tuple[str, dict, object]]:
        """Poll up to max_messages; returns [(key, event, token)].
        Empty list = nothing pending right now."""
        raise NotImplementedError

    def commit(self, tokens: list) -> None:
        """Acknowledge successfully replicated messages."""
        raise NotImplementedError

    def close(self) -> None:
        pass


def _decode(value: bytes | str) -> dict:
    if isinstance(value, (bytes, bytearray)):
        value = value.decode()
    return json.loads(value)


class KafkaInput(NotificationInput):
    """Kafka consumer with offset-file resume
    (notification_kafka.go:88-140: the reference persists per-partition
    progress and seeks there on restart instead of relying on group
    commits)."""

    name = "kafka"

    def __init__(self) -> None:
        self._consumer = None
        self._tp_factory = None
        self.topic = ""
        self.offset_path = ""
        self._offsets: dict[int, int] = {}  # partition -> next offset

    def initialize(self, config: dict, client=None) -> None:
        """config: {"hosts": [...], "topic": ..., "offset_file": path}."""
        self.topic = config.get("topic", "seaweedfs_filer")
        self.offset_path = (config.get("offset_file")
                            or f"./{self.topic}.offset")
        if client is None:
            try:
                import kafka  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "subscription input 'kafka' requires the kafka-python "
                    "client, which is not available in this environment"
                ) from e
            client = kafka.KafkaConsumer(
                bootstrap_servers=config["hosts"],
                enable_auto_commit=False)
            self._tp_factory = kafka.TopicPartition
        else:
            # fakes carry their own TopicPartition shape
            self._tp_factory = (getattr(client, "TopicPartition", None)
                                or (lambda t, p: (t, p)))
        self._consumer = client
        self._offsets = self._load_offsets()
        parts = sorted(client.partitions_for_topic(self.topic) or {0})
        tps = [self._tp_factory(self.topic, p) for p in parts]
        client.assign(tps)
        for tp, p in zip(tps, parts):
            client.seek(tp, self._offsets.get(p, 0))

    def _load_offsets(self) -> dict[int, int]:
        try:
            with open(self.offset_path) as f:
                return {int(k): int(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            return {}

    def _save_offsets(self) -> None:
        tmp = self.offset_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._offsets.items()}, f)
        os.replace(tmp, self.offset_path)

    def receive_batch(self, max_messages: int = 64
                      ) -> list[tuple[str, dict, object]]:
        polled = self._consumer.poll(timeout_ms=100,
                                     max_records=max_messages)
        out = []
        for records in polled.values():
            for r in records:
                key = (r.key.decode() if isinstance(r.key, bytes)
                       else str(r.key))
                out.append((key, _decode(r.value),
                            (getattr(r, "partition", 0), r.offset)))
        return out

    def commit(self, tokens: list) -> None:
        for partition, offset in tokens:
            if offset + 1 > self._offsets.get(partition, 0):
                self._offsets[partition] = offset + 1
        self._save_offsets()

    def close(self) -> None:
        if self._consumer is not None:
            self._consumer.close()


class SqsInput(NotificationInput):
    """AWS SQS consumer: receive -> replicate -> delete
    (notification_aws_sqs.go). Resume is inherent: undeleted messages
    reappear after the visibility timeout."""

    name = "aws_sqs"

    def __init__(self) -> None:
        self._client = None
        self.queue_url = ""

    def initialize(self, config: dict, client=None) -> None:
        """config: {"region": ..., "sqs_queue_name": ...}."""
        if client is None:
            try:
                import boto3  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "subscription input 'aws_sqs' requires boto3, which "
                    "is not available in this environment") from e
            client = boto3.client("sqs", region_name=config.get("region"))
        self._client = client
        self.queue_url = client.get_queue_url(
            QueueName=config["sqs_queue_name"])["QueueUrl"]

    def receive_batch(self, max_messages: int = 10
                      ) -> list[tuple[str, dict, object]]:
        resp = self._client.receive_message(
            QueueUrl=self.queue_url,
            MessageAttributeNames=["key"],
            MaxNumberOfMessages=min(max_messages, 10),
            WaitTimeSeconds=0)
        out = []
        for m in resp.get("Messages", []):
            key = m.get("MessageAttributes", {}).get(
                "key", {}).get("StringValue", "")
            out.append((key, _decode(m["Body"]), m["ReceiptHandle"]))
        return out

    def commit(self, tokens: list) -> None:
        # batch deletes: 10 handles per round trip (SQS API limit)
        batch_api = getattr(self._client, "delete_message_batch", None)
        if batch_api is not None:
            for i in range(0, len(tokens), 10):
                batch_api(QueueUrl=self.queue_url, Entries=[
                    {"Id": str(j), "ReceiptHandle": h}
                    for j, h in enumerate(tokens[i:i + 10])])
            return
        for handle in tokens:
            self._client.delete_message(QueueUrl=self.queue_url,
                                        ReceiptHandle=handle)


class GooglePubSubInput(NotificationInput):
    """GCP Pub/Sub consumer: ensure subscription, pull, ack
    (notification_google_pub_sub.go)."""

    name = "google_pub_sub"

    def __init__(self) -> None:
        self._subscriber = None
        self._sub_path = ""

    def initialize(self, config: dict, client=None) -> None:
        """config: {"project_id": ..., "topic": ...}."""
        topic = config.get("topic", "seaweedfs_filer_topic")
        sub_name = config.get("subscription", topic + "_sub")
        if client is None:
            try:
                from google.cloud import pubsub_v1  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "subscription input 'google_pub_sub' requires "
                    "google-cloud-pubsub, which is not available in this "
                    "environment") from e
            client = pubsub_v1.SubscriberClient()
        self._subscriber = client
        self._sub_path = client.subscription_path(config["project_id"],
                                                  sub_name)
        topic_path = client.topic_path(config["project_id"], topic)
        try:
            client.get_subscription(subscription=self._sub_path)
        except Exception:
            client.create_subscription(name=self._sub_path,
                                       topic=topic_path)

    def receive_batch(self, max_messages: int = 64
                      ) -> list[tuple[str, dict, object]]:
        resp = self._subscriber.pull(subscription=self._sub_path,
                                     max_messages=max_messages,
                                     return_immediately=True)
        out = []
        for rm in resp.received_messages:
            key = dict(rm.message.attributes).get("key", "")
            out.append((key, _decode(rm.message.data), rm.ack_id))
        return out

    def commit(self, tokens: list) -> None:
        if tokens:
            self._subscriber.acknowledge(subscription=self._sub_path,
                                         ack_ids=list(tokens))
