"""s3 subpackage."""
