"""AWS Signature V4 verification + aws-chunked payload decoding.

Reference: weed/s3api/s3api_auth.go:15-85 (auth-type detection: header
signature v4, presigned query v4, anonymous) and chunked_reader_v4.go
(streaming chunk-signature verification for
STREAMING-AWS4-HMAC-SHA256-PAYLOAD uploads).
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from datetime import datetime, timedelta, timezone

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED = "UNSIGNED-PAYLOAD"
STREAMING = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"

# Largest accepted aws-chunked chunk: SDKs send 8KB-1MB chunks; 16MB
# bounds the per-chunk buffering a client-declared size can force.
MAX_CHUNK_SIZE = 16 * 1024 * 1024


class AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _canonical_query(query: "dict[str, str] | list[tuple[str, str]]",
                     drop_signature: bool = False) -> str:
    items = (query.items() if isinstance(query, dict) else query)
    pairs = sorted(
        (urllib.parse.quote(k, safe="-_.~"),
         urllib.parse.quote(v, safe="-_.~"))
        for k, v in items
        if not (drop_signature and k == "X-Amz-Signature"))
    return "&".join(f"{k}={v}" for k, v in pairs)


def _canonical_request(method: str, path: str, cq: str,
                       signed_headers: list[str],
                       headers, payload_hash: str) -> str:
    """`path` must be the RAW (still percent-encoded) request path: S3
    SigV4 signs it verbatim, and requoting a decoded path corrupts keys
    whose encoding is not a decode-requote fixed point (a%2Fb)."""
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    return "\n".join([method, path, cq,
                      canon_headers, ";".join(signed_headers),
                      payload_hash])


def _string_to_sign(amz_date: str, scope: str, canonical: str) -> str:
    return "\n".join([ALGORITHM, amz_date, scope,
                      hashlib.sha256(canonical.encode()).hexdigest()])


class AuthContext:
    """Result of a successful verification: everything the streaming
    chunk-signature check needs (chunked_reader_v4.go keeps the same
    state: seed signature, signing key, date/scope)."""

    def __init__(self, access_key: str, key: bytes, scope: str,
                 amz_date: str, seed_signature: str,
                 content_sha256: str):
        self.access_key = access_key
        self.key = key
        self.scope = scope
        self.amz_date = amz_date
        self.seed_signature = seed_signature
        self.content_sha256 = content_sha256

    _EMPTY_SHA = hashlib.sha256(b"").hexdigest()

    def chunk_signature(self, prev_sig: str, data: bytes) -> str:
        sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", self.amz_date, self.scope,
            prev_sig, self._EMPTY_SHA,
            hashlib.sha256(data).hexdigest()])
        return hmac.new(self.key, sts.encode(), hashlib.sha256).hexdigest()


class SigV4Verifier:
    """Verifies header-based and presigned V4 requests against a static
    identity table {access_key: secret_key} (weed s3 identities model)."""

    def __init__(self, identities: dict[str, str]):
        self.identities = identities

    # -- helpers ----------------------------------------------------------

    def auth_type(self, headers, query) -> str:
        auth = headers.get("Authorization", "")
        if auth.startswith(ALGORITHM):
            return "header"
        if query.get("X-Amz-Algorithm") == ALGORITHM:
            return "presigned"
        if auth:
            return "unsupported"
        return "anonymous"

    def verify(self, method: str, path: str, query, headers,
               payload_hash: str | None) -> "AuthContext":
        """Returns the authenticated AuthContext. Raises AuthError.

        `query` may be a dict or a list of (key, value) pairs — pass the
        pair list when duplicate query keys are possible (dict would
        collapse them and break the canonical query string)."""
        qd = query if isinstance(query, dict) else dict(query)
        kind = self.auth_type(headers, qd)
        if kind == "anonymous":
            raise AuthError("AccessDenied", "anonymous access denied")
        if kind == "unsupported":
            raise AuthError("AccessDenied",
                            "unsupported authorization scheme")
        if kind == "presigned":
            return self._verify_presigned(method, path, query, qd,
                                          headers)
        return self._verify_header(method, path, query, headers,
                                   payload_hash)

    def _secret_for(self, access_key: str) -> str:
        try:
            return self.identities[access_key]
        except KeyError:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key!r}") from None

    def _verify_header(self, method, path, query, headers,
                       payload_hash) -> str:
        auth = headers.get("Authorization", "")
        parts = dict(
            p.strip().split("=", 1)
            for p in auth[len(ALGORITHM):].strip().split(",") if "=" in p)
        try:
            cred = parts["Credential"]
            signed = parts["SignedHeaders"].lower().split(";")
            got_sig = parts["Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"missing {e} in Authorization") from None
        try:
            access_key, date, region, service, _ = cred.split("/", 4)
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"bad Credential {cred!r}") from None
        secret = self._secret_for(access_key)
        amz_date = headers.get("x-amz-date", headers.get("X-Amz-Date", ""))
        # clock-skew window: an unexpiring signature would make any
        # captured request replayable forever (AWS allows 15 minutes)
        try:
            t0 = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=timezone.utc)
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"bad x-amz-date {amz_date!r}") from None
        if abs((datetime.now(timezone.utc) - t0).total_seconds()) > 900:
            raise AuthError("RequestTimeTooSkewed",
                            "request time too far from server time")
        payload = headers.get("x-amz-content-sha256", payload_hash
                              or UNSIGNED)
        scope = f"{date}/{region}/{service}/aws4_request"
        canonical = _canonical_request(
            method, path, _canonical_query(query), signed,
            _lower_headers(headers), payload)
        sts = _string_to_sign(amz_date, scope, canonical)
        key = signing_key(secret, date, region, service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, got_sig):
            raise AuthError("SignatureDoesNotMatch",
                            "request signature mismatch")
        return AuthContext(access_key, key, scope, amz_date, want, payload)

    def _verify_presigned(self, method, path, query, qd,
                          headers) -> "AuthContext":
        cred = qd.get("X-Amz-Credential", "")
        try:
            access_key, date, region, service, _ = \
                urllib.parse.unquote(cred).split("/", 4)
        except ValueError:
            raise AuthError("AuthorizationQueryParametersError",
                            f"bad X-Amz-Credential {cred!r}") from None
        secret = self._secret_for(access_key)
        amz_date = qd.get("X-Amz-Date", "")
        # expiry check
        try:
            t0 = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=timezone.utc)
            expires = int(qd.get("X-Amz-Expires", "0"))
        except ValueError:
            raise AuthError("AuthorizationQueryParametersError",
                            "bad X-Amz-Date/X-Amz-Expires") from None
        # AWS bounds presigned lifetime to 7 days; without this a
        # credential holder could mint effectively non-expiring URLs
        if not 1 <= expires <= 604800:
            raise AuthError("AuthorizationQueryParametersError",
                            "X-Amz-Expires must be in 1..604800")
        now = datetime.now(timezone.utc)
        # a far-future X-Amz-Date would extend the lifetime past the
        # X-Amz-Expires cap; apply the header-auth 15-minute skew window
        if (t0 - now).total_seconds() > 900:
            raise AuthError("RequestTimeTooSkewed",
                            "X-Amz-Date too far in the future")
        if now > t0 + timedelta(seconds=expires):
            raise AuthError("AccessDenied", "request has expired")
        signed = qd.get("X-Amz-SignedHeaders", "host").split(";")
        scope = f"{date}/{region}/{service}/aws4_request"
        canonical = _canonical_request(
            method, path, _canonical_query(query, drop_signature=True),
            signed, _lower_headers(headers), UNSIGNED)
        sts = _string_to_sign(amz_date, scope, canonical)
        key = signing_key(secret, date, region, service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, qd.get("X-Amz-Signature", "")):
            raise AuthError("SignatureDoesNotMatch",
                            "presigned signature mismatch")
        return AuthContext(access_key, key, scope, amz_date, want, UNSIGNED)


def _lower_headers(headers) -> dict:
    """Lower-cased header dict for canonicalization. SigV4 requires
    repeated headers to be comma-joined (after whitespace folding), so a
    multidict source (aiohttp CIMultiDict) must not collapse to the last
    value — a client legitimately signing a duplicated header (repeated
    x-amz-meta-*) would get a spurious SignatureDoesNotMatch."""
    if hasattr(headers, "getall"):
        out: dict = {}
        for k in headers.keys():
            lk = k.lower()
            if lk not in out:
                out[lk] = ",".join(
                    " ".join(v.split()) for v in headers.getall(k))
        return out
    return {k.lower(): v for k, v in headers.items()}


def decode_aws_chunked(body: bytes,
                       ctx: "AuthContext | None" = None) -> bytes:
    """Whole-buffer convenience wrapper over AwsChunkedDecoder (single
    framing implementation; same validation rules)."""
    import asyncio
    import io

    class _Reader:
        def __init__(self, data: bytes):
            self._f = io.BytesIO(data)

        async def readline(self) -> bytes:
            return self._f.readline()

        async def read(self, n: int) -> bytes:
            return self._f.read(n)

        async def readexactly(self, n: int) -> bytes:
            data = self._f.read(n)
            if len(data) != n:
                raise asyncio.IncompleteReadError(data, n)
            return data

    async def run() -> bytes:
        return await AwsChunkedDecoder(_Reader(body), ctx).read()

    return asyncio.run(run())


class AwsChunkedDecoder:
    """Streaming decoder over an aiohttp StreamReader for
    STREAMING-AWS4-HMAC-SHA256-PAYLOAD bodies (chunked_reader_v4.go):
    strips the `<hex-size>;chunk-signature=<sig>\\r\\n ... \\r\\n` framing
    and exposes the same `await read(n)` surface the store path uses.

    With an AuthContext, every chunk signature is verified against the
    AWS4-HMAC-SHA256-PAYLOAD chain seeded by the request signature — a
    tampered or reordered chunk raises AuthError mid-stream. Without one
    (anonymous gateway), only the framing is parsed."""

    def __init__(self, raw, ctx: "AuthContext | None" = None):
        self.raw = raw
        self.ctx = ctx
        self.prev_sig = ctx.seed_signature if ctx else ""
        self.buf = b""
        self.done = False

    async def _next_chunk(self) -> None:
        line = await self.raw.readline()
        while line in (b"\r\n", b"\n"):
            line = await self.raw.readline()
        if not line:
            # EOF before the terminal 0-size chunk: the stream's sealing
            # signature was never presented — a truncated body must not
            # be stored as a complete object
            raise AuthError("IncompleteBody",
                            "stream ended before the final chunk")
        header = line.strip().decode("ascii", "replace")
        size_hex, _, rest = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise AuthError("IncompleteBody",
                            f"bad chunk header {header[:40]!r}") from None
        if size < 0 or size > MAX_CHUNK_SIZE:
            # the declared size is buffered via readexactly before its
            # signature can be checked; an attacker-controlled multi-GB
            # claim must not force unbounded gateway memory (streaming
            # bodies bypass aiohttp's client_max_size)
            raise AuthError("InvalidRequest",
                            f"chunk size {size} exceeds {MAX_CHUNK_SIZE}")
        sig = ""
        for kv in rest.split(";"):
            if kv.startswith("chunk-signature="):
                sig = kv[len("chunk-signature="):]
        data = await self.raw.readexactly(size) if size else b""
        if size:
            await self.raw.readexactly(2)  # chunk-trailing \r\n
        if self.ctx is not None:
            want = self.ctx.chunk_signature(self.prev_sig, data)
            if not hmac.compare_digest(want, sig):
                raise AuthError("SignatureDoesNotMatch",
                                "chunk signature mismatch")
            self.prev_sig = want
        if size == 0:
            while True:  # swallow trailers until the blank terminator
                t = await self.raw.readline()
                if t in (b"", b"\r\n", b"\n"):
                    break
            self.done = True
        else:
            self.buf = data

    async def read(self, n: int = -1) -> bytes:
        out = bytearray()
        while not self.done and (n < 0 or len(out) < n):
            if not self.buf:
                await self._next_chunk()
                if self.done or not self.buf:
                    break
            take = len(self.buf) if n < 0 else min(len(self.buf),
                                                   n - len(out))
            out += self.buf[:take]
            self.buf = self.buf[take:]
        return bytes(out)


def is_aws_chunked(headers) -> bool:
    return (headers.get("x-amz-content-sha256") == STREAMING
            or "aws-chunked" in headers.get("Content-Encoding", ""))
