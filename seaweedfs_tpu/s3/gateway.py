"""S3 REST gateway over the filer.

Reference: weed/s3api/ — router (s3api_server.go:31-107), bucket handlers
(bucket == collection, stored under /buckets/<name>), object passthrough,
multipart uploads assembled from part files (filer_multipart.go:25-121),
ListObjects w/ prefix/marker/delimiter, bulk delete. XML shapes follow
AmazonS3.xsd (s3api_xsd_generated.go).

The gateway holds the Filer in-proc (like `weed server -s3`) and streams
chunk data through the volume tier with WeedClient.
"""

from __future__ import annotations

import asyncio
import hashlib
import math
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

import aiohttp
from aiohttp import web

from ..filer.entry import Attr, Entry, new_directory_entry
from .auth import (AuthError, AwsChunkedDecoder, SigV4Verifier,
                   is_aws_chunked)
from ..filer.filechunks import FileChunk, etag as chunks_etag, view_from_chunks
from ..filer.stream import stream_chunk_views
from ..filer.filer import Filer, FilerError
from ..util.client import OperationError, WeedClient
from ..util.httprange import RangeError, parse_range
from ..security import tls

BUCKETS_DIR = "/buckets"
UPLOADS_DIR = "/buckets/.uploads"
_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _xml(root: ET.Element) -> web.Response:
    body = b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)
    return web.Response(body=body, content_type="application/xml")


def _err(code: str, message: str, status: int) -> web.Response:
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = code
    ET.SubElement(root, "Message").text = message
    return web.Response(
        body=b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root),
        content_type="application/xml", status=status)


def _shed_response(dec) -> web.Response:
    """AWS-shaped throttle answer: the `SlowDown` error XML aws-sdk
    clients back off on natively, with `Retry-After` derived from the
    tenant's own bucket refill (integer delta-seconds, rounded up so
    a sub-second refill never reads as 'retry immediately')."""
    resp = _err("SlowDown", "Please reduce your request rate.",
                dec.status)
    resp.headers["Retry-After"] = \
        str(max(1, math.ceil(dec.retry_after_s)))
    return resp


def _ts(t: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(t))


def _auth_status(e: AuthError) -> int:
    return (403 if e.code in ("AccessDenied", "SignatureDoesNotMatch",
                              "InvalidAccessKeyId")
            else 400)


class S3Gateway:
    def __init__(self, filer: Filer, master_url: str,
                 ip: str = "127.0.0.1", port: int = 8333,
                 chunk_size: int = 8 * 1024 * 1024,
                 identities: dict[str, str] | None = None,
                 domain_name: str = "",
                 cache_mem_bytes: int = 0,
                 cache_dir: str = "",
                 admission=None,
                 shard_router=None):
        # -cache.mem/-cache.dir chunk read cache (see FilerServer)
        self.cache_mem_bytes = cache_mem_bytes
        self.cache_dir = cache_dir
        # sharded gateway fleet (filer/shard.py GatewayRouter): one
        # gateway per filer shard; foreign-bucket requests bounce to
        # the sibling with 307 + X-Shard-Owner
        self.shard_router = shard_router
        self._shard_http: aiohttp.ClientSession | None = None
        # -domainName (s3api_server.go:35-37): virtual-host-style
        # addressing, Host: <bucket>.<domainName>
        self.domain_name = domain_name
        self.filer = filer
        self.master_url = master_url
        self.ip = ip
        self.port = port
        self.chunk_size = chunk_size
        # {access_key: secret_key}; empty == anonymous mode
        # (s3api_auth.go authTypeAnonymous when no identities configured)
        self.identities = dict(identities or {})
        self._verifier = SigV4Verifier(self.identities)
        # explicit AdmissionController for tests; daemons leave None
        # and the middleware consults the process singleton live
        self.admission = admission
        self.client: WeedClient | None = None
        self._runner: web.AppRunner | None = None
        self.app = self._build_app()

    def _admission(self):
        from .. import qos
        return self.admission if self.admission is not None \
            else qos.admission()

    def _build_app(self) -> web.Application:
        from ..util import tracing
        app = web.Application(client_max_size=5 * 1024 * 1024 * 1024,
                              middlewares=[self._auth_middleware])
        # reserved introspection paths FIRST (route order wins): the
        # trace ring of this gateway process, mirroring the volume
        # server's /debug/traces (documented caveat: shadows a bucket
        # literally named __debug__); shared handlers, no drift
        h_traces, h_requests = tracing.debug_handlers()
        app.router.add_get("/__debug__/traces", h_traces)
        app.router.add_get("/__debug__/requests", h_requests)
        # flight-recorder twins: same shared trio as master/filer/WebDAV
        from ..stats.timeline import recorder_handlers
        h_tl, h_ev, h_hl = recorder_handlers()
        app.router.add_get("/__debug__/timeline", h_tl)
        app.router.add_post("/__debug__/timeline", h_tl)
        app.router.add_get("/__debug__/events", h_ev)
        app.router.add_get("/__debug__/health", h_hl)
        from .. import qos
        app.router.add_get("/__debug__/qos", qos.debug_handler)
        from ..stats import profiler
        from ..util import pprof
        app.router.add_get("/__debug__/profile", profiler.debug_handler())
        app.router.add_get("/__debug__/pprof", pprof.debug_handler())
        # the qos soak arms/disarms `qos.admit` here at runtime, the
        # same shared admin surface the volume/master/filer expose
        from ..util import failpoints
        app.router.add_route("*", "/__debug__/failpoints",
                             failpoints.handle_debug)
        # "*": with -domainName, PUT/DELETE bucket.domain/ are bucket
        # operations that land on the root path
        app.router.add_route("*", "/", self.h_list_buckets)
        app.router.add_route("*", "/{bucket}", self.h_bucket)
        app.router.add_route("*", "/{bucket}/{key:.+}", self.h_object)
        return app

    @web.middleware
    async def _auth_middleware(self, req: web.Request, handler):
        from .. import qos
        from ..util import tracing
        # the reserved introspection paths are NOT S3 objects and are
        # served unsigned, exactly like every other tier's /debug
        # surface (the bucket-shadowing caveat above already applies)
        debug = req.path.startswith("/__debug__")
        if self.shard_router is not None and not debug \
                and req.path != "/":
            owner = await self.shard_router.foreign_owner(
                self._shard_http, BUCKETS_DIR + req.path)
            if owner:
                self.shard_router.redirects += 1
                return web.Response(
                    status=307,
                    headers={"Location": tls.url(owner, req.path_qs),
                             "X-Shard-Owner": owner,
                             "X-Shard-Prefix":
                                 self.shard_router.matched_prefix(
                                     BUCKETS_DIR + req.path),
                             "X-Shard-Epoch": str(
                                 self.shard_router.routes.map.epoch)})
        if self.identities and not debug:
            try:
                # raw_path: SigV4 signs the encoded form verbatim, and a
                # decode-requote round trip corrupts keys like a%2Fb;
                # items list: dict() would collapse duplicate query keys
                req["s3auth"] = self._verifier.verify(
                    req.method, req.rel_url.raw_path,
                    list(req.query.items()), req.headers, None)
            except AuthError as e:
                return _err(e.code, str(e), _auth_status(e))
        op = req.method.lower()
        # tenant admission AFTER auth (the identity is the verified
        # access key — an unsigned scan can't impersonate a class) and
        # BEFORE the handler: a shed request costs no filer/volume work
        ctrl = None if debug else self._admission()
        dec = None
        if ctrl is not None:
            ctx = req.get("s3auth")
            # weedlint: ignore[lock-acquire] admission decision, not a mutex: a denied Decision holds nothing, and the admitted path releases in the finally below
            dec = await ctrl.acquire(
                "s3", op, getattr(ctx, "access_key", "") if ctx else "")
            if not dec.admitted:
                return _shed_response(dec)
            qos.set_current_class(dec.cls)
        sp = (tracing._NOOP if debug
              else tracing.start_root(
                  "s3", op, headers=req.headers,
                  **({"tenant": dec.tenant} if dec is not None else {})))
        t0 = time.perf_counter()
        try:
            with sp:
                try:
                    resp = await handler(req)
                except AuthError as e:
                    # mid-stream chunk-signature / truncation failures
                    sp.status = "auth"
                    return _err(e.code, str(e), _auth_status(e))
                except web.HTTPException as e:
                    sp.status = str(e.status)
                    raise
                sp.status = "ok" if resp.status < 400 \
                    else str(resp.status)
                return resp
        finally:
            if dec is not None:
                ctrl.release(dec)
                ctrl.observe("s3", op, dec,
                             time.perf_counter() - t0)

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    async def start(self) -> None:
        cc = None
        if self.cache_mem_bytes > 0:
            from ..util import tracing
            from ..util.chunk_cache import TieredChunkCache
            # ctor makedirs the disk tier — off the loop: under
            # `weed-tpu server` this loop already serves other daemons
            cc = await tracing.run_in_executor(
                lambda: TieredChunkCache(self.cache_mem_bytes,
                                         disk_dir=self.cache_dir or None))
        self.client = WeedClient(self.master_url, chunk_cache=cc)
        await self.client.__aenter__()
        if self.shard_router is not None:
            self._shard_http = tls.make_session(
                timeout=aiohttp.ClientTimeout(total=10))
        # when standalone (no colocated FilerServer draining chunk GC),
        # run our own drain loop so deletes/overwrites reclaim blobs
        self._gc_task: asyncio.Task | None = None
        if self.filer.chunk_deleter is None:
            self._pending: list[str] = []
            self.filer.chunk_deleter = self._pending.extend
            self._gc_task = asyncio.create_task(self._chunk_gc_loop())
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.ip, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]

    async def _chunk_gc_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            batch, self._pending = self._pending[:1024], self._pending[1024:]
            if batch:
                try:
                    await self.client.delete_fids(batch)
                except Exception:
                    self._pending.extend(batch)

    async def stop(self) -> None:
        if self._gc_task:
            self._gc_task.cancel()
        if self._shard_http is not None:
            await self._shard_http.close()
        if self.client:
            await self.client.__aexit__()
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------------
    # buckets
    # ------------------------------------------------------------------

    def _host_bucket(self, req: web.Request) -> "str | None":
        """Bucket named by a virtual-host-style Host header
        (s3api_server.go:35-37), else None for path-style."""
        if not self.domain_name:
            return None
        host = req.headers.get("Host", "").split(":")[0]
        suffix = "." + self.domain_name
        if host.endswith(suffix):
            bucket = host[: -len(suffix)]
            # an empty label ('Host: .domain') would alias the whole
            # /buckets root — a single malformed header must never turn
            # DELETE / into delete-every-bucket
            if bucket and "/" not in bucket:
                return bucket
        return None

    async def h_list_buckets(self, req: web.Request) -> web.Response:
        hb = self._host_bucket(req)
        if hb is not None:
            # bucket.domain/ is a bucket operation, not ListBuckets
            return await self._bucket_ops(req, hb)
        if req.method != "GET":
            return _err("MethodNotAllowed", req.method, 405)
        return await self._list_buckets(req)

    async def _list_buckets(self, req: web.Request) -> web.Response:
        root = ET.Element("ListAllMyBucketsResult", xmlns=_NS)
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = "seaweedfs_tpu"
        buckets = ET.SubElement(root, "Buckets")
        for e in self.filer.list_directory_entries(BUCKETS_DIR, limit=10000):
            if not e.is_directory or e.name.startswith("."):
                continue
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = e.name
            ET.SubElement(b, "CreationDate").text = _ts(e.attr.crtime)
        return _xml(root)

    async def h_bucket(self, req: web.Request) -> web.Response:
        hb = self._host_bucket(req)
        if hb is not None:
            # host-style: the single path segment is an object key
            return await self._object_ops(
                req, hb, urllib.parse.unquote(req.match_info["bucket"]))
        return await self._bucket_ops(req, req.match_info["bucket"])

    async def _bucket_ops(self, req: web.Request,
                          bucket: str) -> web.Response:
        path = f"{BUCKETS_DIR}/{bucket}"
        if req.method == "PUT":
            self.filer.create_entry(new_directory_entry(path))
            return web.Response(status=200)
        if req.method == "HEAD":
            e = self.filer.find_entry(path)
            if e is None:
                return web.Response(status=404)
            return web.Response(status=200)
        if req.method == "DELETE":
            try:
                self.filer.delete_entry(path, recursive=True,
                                        ignore_recursive_error=True)
            except FilerError:
                return _err("NoSuchBucket", bucket, 404)
            return web.Response(status=204)
        if req.method == "POST" and "delete" in req.query:
            return await self._bulk_delete(req, bucket)
        if req.method == "GET":
            if self.filer.find_entry(path) is None:
                return _err("NoSuchBucket", bucket, 404)
            if "uploads" in req.query:
                return self._list_multipart_uploads(bucket)
            return await self._list_objects(req, bucket)
        return _err("MethodNotAllowed", req.method, 405)

    def _list_multipart_uploads(self, bucket: str) -> web.Response:
        """ListMultipartUploads (s3api_server.go:59): every in-progress
        upload targeting this bucket, from the shared uploads dir."""
        root = ET.Element("ListMultipartUploadsResult", xmlns=_NS)
        ET.SubElement(root, "Bucket").text = bucket
        # page through the SHARED uploads dir completely — a capped
        # single listing would silently drop uploads for this bucket
        # once the global in-progress count passes the cap
        ups: list = []
        start = ""
        while True:
            try:
                page = self.filer.list_directory_entries(
                    UPLOADS_DIR, start, False, 1024)
            except FilerError:
                break
            ups.extend(page)
            if len(page) < 1024:
                break
            start = page[-1].name
        for e in ups:
            meta = e.extended or {}
            if meta.get("bucket") != bucket:
                continue
            el = ET.SubElement(root, "Upload")
            ET.SubElement(el, "Key").text = str(meta.get("key", ""))
            ET.SubElement(el, "UploadId").text = e.name
            ET.SubElement(el, "Initiated").text = _ts(e.attr.crtime)
        return _xml(root)

    async def _list_objects(self, req: web.Request,
                            bucket: str) -> web.Response:
        q = req.query
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", 1000))
        marker = q.get("continuation-token" if v2 else "marker", "")

        keys, prefixes, truncated, next_marker = self._walk_objects(
            bucket, prefix, delimiter, marker, max_keys)

        root = ET.Element("ListBucketResult", xmlns=_NS)
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = prefix
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        ET.SubElement(root, "IsTruncated").text = \
            "true" if truncated else "false"
        if v2:
            ET.SubElement(root, "KeyCount").text = str(len(keys))
            if truncated:
                ET.SubElement(root, "NextContinuationToken").text = \
                    next_marker
        elif truncated:
            ET.SubElement(root, "NextMarker").text = next_marker
        for key, e in keys:
            c = ET.SubElement(root, "Contents")
            ET.SubElement(c, "Key").text = key
            ET.SubElement(c, "LastModified").text = _ts(e.attr.mtime)
            ET.SubElement(c, "ETag").text = f'"{chunks_etag(e.chunks)}"'
            ET.SubElement(c, "Size").text = str(e.size)
            ET.SubElement(c, "StorageClass").text = "STANDARD"
        for p in sorted(prefixes):
            cp = ET.SubElement(root, "CommonPrefixes")
            ET.SubElement(cp, "Prefix").text = p
        return _xml(root)

    def _walk_objects(self, bucket: str, prefix: str, delimiter: str,
                      marker: str, max_keys: int):
        """Depth-first walk of the bucket subtree, emitting keys > marker
        matching prefix; delimiter folds into CommonPrefixes."""
        base = f"{BUCKETS_DIR}/{bucket}"
        keys: list[tuple[str, Entry]] = []
        prefixes: set[str] = set()
        truncated = False
        next_marker = ""

        def emit(key: str, e: Entry) -> bool:
            nonlocal truncated, next_marker
            if len(keys) >= max_keys:
                truncated = True
                return False
            keys.append((key, e))
            next_marker = key
            return True

        def walk(dir_path: str) -> bool:
            rel_dir = dir_path[len(base):].lstrip("/")
            start = ""
            while True:
                entries = self.filer.list_directory_entries(
                    dir_path, start, False, 1024)
                if not entries:
                    return True
                for e in entries:
                    key = (rel_dir + "/" if rel_dir else "") + e.name
                    if prefix and not key.startswith(prefix) \
                            and not prefix.startswith(key + "/"):
                        continue
                    if delimiter:
                        rest = key[len(prefix):]
                        if delimiter in rest:
                            cut = key[:len(prefix) + rest.index(delimiter)
                                      + len(delimiter)]
                            prefixes.add(cut)
                            continue
                    if e.is_directory:
                        if not walk(f"{dir_path}/{e.name}"):
                            return False
                        continue
                    if marker and key <= marker:
                        continue
                    if not emit(key, e):
                        return False
                start = entries[-1].name
                if len(entries) < 1024:
                    return True

        walk(base)
        return keys, prefixes, truncated, next_marker

    async def _bulk_delete(self, req: web.Request,
                           bucket: str) -> web.Response:
        body = await req.read()
        doc = ET.fromstring(body)
        deleted, errors = [], []
        for obj in doc.findall(".//{*}Object"):
            key_el = obj.find("{*}Key")
            key = key_el.text if key_el is not None else None
            if not key:
                continue
            try:
                self.filer.delete_entry(f"{BUCKETS_DIR}/{bucket}/{key}")
                deleted.append(key)
            except FilerError as e:
                errors.append((key, str(e)))
        root = ET.Element("DeleteResult", xmlns=_NS)
        for key in deleted:
            d = ET.SubElement(root, "Deleted")
            ET.SubElement(d, "Key").text = key
        for key, msg in errors:
            er = ET.SubElement(root, "Error")
            ET.SubElement(er, "Key").text = key
            ET.SubElement(er, "Message").text = msg
        return _xml(root)

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------

    async def h_object(self, req: web.Request) -> web.Response:
        bucket = req.match_info["bucket"]
        key = urllib.parse.unquote(req.match_info["key"])
        hb = self._host_bucket(req)
        if hb is not None:
            # host-style: the first path segment belongs to the key
            bucket, key = hb, f"{urllib.parse.unquote(bucket)}/{key}"
        return await self._object_ops(req, bucket, key)

    async def _object_ops(self, req: web.Request, bucket: str,
                          key: str) -> web.Response:
        path = f"{BUCKETS_DIR}/{bucket}/{key}"
        q = req.query
        if "uploadId" in q or "uploads" in q:
            return await self._multipart(req, bucket, key)
        if req.method == "PUT":
            src = req.headers.get("x-amz-copy-source")
            if src:
                return await self._copy_object(src, path)
            return await self._put_object(req, bucket, path)
        if req.method in ("GET", "HEAD"):
            return await self._get_object(req, path)
        if req.method == "DELETE":
            try:
                self.filer.delete_entry(path)
            except FilerError:
                pass  # S3 delete is idempotent
            return web.Response(status=204)
        return _err("MethodNotAllowed", req.method, 405)

    async def _put_object(self, req: web.Request, bucket: str,
                          path: str) -> web.Response:
        if self.filer.find_entry(f"{BUCKETS_DIR}/{bucket}") is None:
            return _err("NoSuchBucket", bucket, 404)
        mime = req.headers.get("Content-Type", "")
        # filer-tier write span: the chunk fan-out + entry commit of
        # this object write, with the volume uploads as client children
        from ..util import tracing
        with tracing.start("filer", "write") as sp:
            chunks, md5, sha_hex = await self._store_stream(
                self._body_reader(req), collection=bucket, mime=mime)
            if (bad := self._payload_hash_mismatch(req, chunks,
                                                   sha_hex)):
                sp.status = "error"
                return bad
            now = time.time()
            entry = Entry(path, Attr(mtime=now, crtime=now, mime=mime,
                                     collection=bucket), chunks)
            try:
                self.filer.create_entry(entry)
            except FilerError as e:
                self.filer.delete_chunks([c.file_id for c in chunks])
                sp.status = "error"
                return _err("InternalError", str(e), 500)
            sp.set("chunks", len(chunks))
            sp.nbytes = sum(c.size for c in chunks)
        return web.Response(status=200,
                            headers={"ETag": f'"{md5.hexdigest()}"'})

    def _payload_hash_mismatch(self, req: web.Request, chunks,
                               sha_hex: str) -> web.Response | None:
        """When the client signed a concrete payload hash, enforce it —
        otherwise a replayed signature could smuggle a different body.
        Cleans up the uploaded chunks on mismatch."""
        ctx = req.get("s3auth")
        if ctx is not None and len(ctx.content_sha256) == 64 \
                and ctx.content_sha256 != sha_hex:
            self.filer.delete_chunks([c.file_id for c in chunks])
            return _err("XAmzContentSHA256Mismatch",
                        "payload does not match signed hash", 400)
        return None

    def _body_reader(self, req: web.Request):
        """Raw body stream, stripping aws-chunked signature framing when
        the SDK streams with STREAMING-AWS4-HMAC-SHA256-PAYLOAD; chunk
        signatures are verified when the request was authenticated."""
        if is_aws_chunked(req.headers):
            return AwsChunkedDecoder(req.content, req.get("s3auth"))
        return req.content

    async def _store_stream(self, reader, collection: str,
                            mime: str = "") -> tuple[list[FileChunk], object]:
        chunks: list[FileChunk] = []
        offset = 0
        md5 = hashlib.md5()
        sha256 = hashlib.sha256()
        try:
            await self._store_stream_inner(reader, collection, mime,
                                           chunks, md5, sha256)
        except Exception:
            # mid-stream failure (bad chunk signature, truncated body,
            # volume error): the already-uploaded chunks must not leak
            self.filer.delete_chunks([c.file_id for c in chunks])
            raise
        return chunks, md5, sha256.hexdigest()

    async def _store_stream_inner(self, reader, collection, mime,
                                  chunks, md5, sha256) -> None:
        offset = 0
        while True:
            try:
                data = bytearray()
                while len(data) < self.chunk_size:
                    part = await reader.read(self.chunk_size - len(data))
                    if not part:
                        break
                    data.extend(part)
            except asyncio.IncompleteReadError:
                raise AuthError("IncompleteBody",
                                "request body ended mid-chunk") from None
            if not data:
                break
            md5.update(data)
            sha256.update(data)
            a = await self.client.assign(collection=collection)
            up = await self.client.upload(a["fid"], a["url"], bytes(data),
                                          mime=mime,
                                          auth=a.get("auth", ""))
            chunks.append(FileChunk(a["fid"], offset, len(data),
                                    time.time_ns(), up.get("eTag", "")))
            offset += len(data)
            if len(data) < self.chunk_size:
                break

    async def _copy_object(self, src: str, dst_path: str) -> web.Response:
        src = urllib.parse.unquote(src).lstrip("/")
        src_path = f"{BUCKETS_DIR}/{src}"
        entry = self.filer.find_entry(src_path)
        if entry is None:
            return _err("NoSuchKey", src, 404)
        # server-side copy re-uploads chunk data (fresh fids, so source
        # delete cannot orphan the copy)
        new_chunks: list[FileChunk] = []
        for view in view_from_chunks(entry.chunks, 0, entry.size):
            data = await self.client.read(view.file_id, view.offset,
                                          view.size)
            a = await self.client.assign(
                collection=dst_path.split("/")[2])
            up = await self.client.upload(a["fid"], a["url"], data,
                                          auth=a.get("auth", ""))
            new_chunks.append(FileChunk(
                a["fid"], view.logic_offset, view.size, time.time_ns(),
                up.get("eTag", "")))
        now = time.time()
        self.filer.create_entry(Entry(
            dst_path, Attr(mtime=now, crtime=now, mime=entry.attr.mime),
            new_chunks))
        root = ET.Element("CopyObjectResult", xmlns=_NS)
        ET.SubElement(root, "ETag").text = f'"{chunks_etag(new_chunks)}"'
        ET.SubElement(root, "LastModified").text = _ts(now)
        return _xml(root)

    async def _get_object(self, req: web.Request,
                          path: str) -> web.StreamResponse:
        entry = self.filer.find_entry(path)
        if entry is None or entry.is_directory:
            return _err("NoSuchKey", path, 404)
        size = entry.size
        offset, length, status = 0, size, 200
        try:
            rng = parse_range(req.headers.get("Range", ""), size)
        except RangeError as e:
            return _err("InvalidRange", str(e), 416)
        if rng is not None:
            offset, length = rng
            status = 206
        headers = {
            "ETag": f'"{chunks_etag(entry.chunks)}"',
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(entry.attr.mtime)),
            "Content-Length": str(length),
            "Accept-Ranges": "bytes",
        }
        if status == 206:
            headers["Content-Range"] = \
                f"bytes {offset}-{offset+length-1}/{size}"
        ct = entry.attr.mime or "application/octet-stream"
        if req.method == "HEAD":
            return web.Response(status=status, headers=headers,
                                content_type=ct)
        resp = web.StreamResponse(status=status, headers=headers)
        resp.content_type = ct
        await resp.prepare(req)
        # filer-tier stream span: the chunk fan-out/assembly cost of
        # this object read, with the volume hops as client children
        from ..util import tracing
        with tracing.start("filer", "stream",
                           chunks=len(entry.chunks)) as sp:
            try:
                sent = 0
                async for data in stream_chunk_views(
                        self.client, entry.chunks, offset, length):
                    await resp.write(data)
                    sent += len(data)
                sp.nbytes = sent
            except OperationError:
                sp.status = "error"
                if req.transport is not None:
                    req.transport.close()
                return resp
        await resp.write_eof()
        return resp

    # ------------------------------------------------------------------
    # multipart (filer_multipart.go)
    # ------------------------------------------------------------------

    async def _multipart(self, req: web.Request, bucket: str,
                         key: str) -> web.Response:
        q = req.query
        if req.method == "POST" and "uploads" in q:
            upload_id = uuid.uuid4().hex
            d = new_directory_entry(f"{UPLOADS_DIR}/{upload_id}")
            d.extended = {"bucket": bucket, "key": key}
            self.filer.create_entry(d)
            root = ET.Element("InitiateMultipartUploadResult", xmlns=_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = upload_id
            return _xml(root)

        upload_id = q.get("uploadId", "")
        updir = f"{UPLOADS_DIR}/{upload_id}"
        if self.filer.find_entry(updir) is None:
            return _err("NoSuchUpload", upload_id, 404)

        if req.method == "PUT" and "partNumber" in q:
            part = int(q["partNumber"])
            chunks, md5, sha_hex = await self._store_stream(
                self._body_reader(req), collection=bucket)
            if (bad := self._payload_hash_mismatch(req, chunks, sha_hex)):
                return bad
            now = time.time()
            self.filer.create_entry(Entry(
                f"{updir}/{part:04d}.part", Attr(mtime=now, crtime=now),
                chunks))
            return web.Response(status=200,
                                headers={"ETag": f'"{md5.hexdigest()}"'})

        if req.method == "POST":  # CompleteMultipartUpload
            parts = self.filer.list_directory_entries(updir, limit=10001)
            parts = sorted((p for p in parts
                            if p.name.endswith(".part")),
                           key=lambda p: int(p.name.split(".")[0]))
            all_chunks: list[FileChunk] = []
            offset = 0
            for p in parts:
                for c in sorted(p.chunks, key=lambda c: c.offset):
                    all_chunks.append(FileChunk(
                        c.file_id, offset + c.offset, c.size, c.mtime,
                        c.etag))
                offset += p.size
            now = time.time()
            path = f"{BUCKETS_DIR}/{bucket}/{key}"
            self.filer.create_entry(Entry(
                path, Attr(mtime=now, crtime=now, collection=bucket),
                all_chunks))
            # drop part entries WITHOUT freeing chunks (now referenced by
            # the object): bypass delete_entry's chunk GC
            for p in parts:
                self.filer.store.delete_entry(p.full_path)
            self.filer.store.delete_entry(updir)
            root = ET.Element("CompleteMultipartUploadResult", xmlns=_NS)
            ET.SubElement(root, "Location").text = \
                tls.url(self.url, f"/{bucket}/{key}")
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "ETag").text = \
                f'"{chunks_etag(all_chunks)}-{len(parts)}"'
            return _xml(root)

        if req.method == "DELETE":  # AbortMultipartUpload
            try:
                self.filer.delete_entry(updir, recursive=True,
                                        ignore_recursive_error=True)
            except FilerError:
                pass
            return web.Response(status=204)

        if req.method == "GET":  # ListParts
            parts = self.filer.list_directory_entries(updir, limit=10001)
            root = ET.Element("ListPartsResult", xmlns=_NS)
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = upload_id
            for p in sorted(parts, key=lambda p: p.name):
                if not p.name.endswith(".part"):
                    continue
                el = ET.SubElement(root, "Part")
                ET.SubElement(el, "PartNumber").text = \
                    str(int(p.name.split(".")[0]))
                ET.SubElement(el, "Size").text = str(p.size)
                ET.SubElement(el, "LastModified").text = _ts(p.attr.mtime)
            return _xml(root)

        return _err("MethodNotAllowed", req.method, 405)
