"""security subpackage."""
