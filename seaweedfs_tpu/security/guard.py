"""IP white-list guard.

Reference: weed/security/guard.go:43-137 — handlers wrapped with
`Guard.WhiteList` admit everyone when the list is empty, otherwise only
peers whose IP matches an entry (exact IP or CIDR network); mismatches
get 401. Wired via -whiteList on master/volume
(weed/command/volume.go:87,125, master.go).
"""

from __future__ import annotations

import ipaddress


class Guard:
    def __init__(self, white_list: "list[str] | tuple[str, ...]" = ()):
        self.nets: list = []
        self.ips: set = set()
        for entry in white_list or ():
            entry = entry.strip()
            if not entry:
                continue
            # validate every entry at parse time — a typo'd IP that can
            # never match would silently lock out the intended peer
            if "/" in entry:
                self.nets.append(
                    ipaddress.ip_network(entry, strict=False))
            else:
                self.ips.add(ipaddress.ip_address(entry))

    @property
    def empty(self) -> bool:
        return not self.ips and not self.nets

    def allows(self, ip: "str | None") -> bool:
        if self.empty:
            return True
        if not ip:
            return False
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return False
        return addr in self.ips or any(addr in net for net in self.nets)


def middleware(guard_getter, is_guarded, remote_of=None):
    """Shared aiohttp middleware: 401 when the live guard rejects the
    peer of a guarded request. guard_getter is late-bound so a server's
    guard can be swapped at runtime (tests do). remote_of lets -workers
    servers substitute the token-authenticated X-Forwarded-For peer for
    intra-host proxy hops (server/workers.py)."""
    from aiohttp import web

    @web.middleware
    async def white_list_mw(req, handler):
        g = guard_getter()
        remote = remote_of(req) if remote_of is not None else req.remote
        if not g.empty and is_guarded(req) and not g.allows(remote):
            return web.json_response({"error": "ip not in whitelist"},
                                     status=401)
        return await handler(req)

    return white_list_mw


def path_guarded(path: str, prefixes) -> bool:
    """True when `path` is one of the guarded endpoints.

    Matches exact path or a sub-path (prefix + '/'); a bare
    startswith() would also guard unrelated siblings like
    /submitfoo. Entries already ending in '/' guard the subtree."""
    for p in prefixes:
        if p.endswith("/"):
            if path.startswith(p):
                return True
        elif path == p or path.startswith(p + "/"):
            return True
    return False


def parse_white_list(spec: str) -> list[str]:
    """Comma-separated -whiteList flag value -> entries.

    Validates eagerly so a typo'd entry fails the command cleanly
    instead of dying later with an ipaddress traceback."""
    entries = [e.strip() for e in (spec or "").split(",") if e.strip()]
    for entry in entries:
        try:
            if "/" in entry:
                ipaddress.ip_network(entry, strict=False)
            else:
                ipaddress.ip_address(entry)
        except ValueError as e:
            raise SystemExit(
                f"invalid -whiteList entry {entry!r}: {e}") from None
    return entries
