"""JWT (HS256) write tokens, minted by the master per fileId and checked by
volume servers.

Reference: weed/security/jwt.go:140-180 (SeaweedFileIdClaims), guard.go
(white-list + jwt guard), wired at master_server.go:71-78 and
volume_server_handlers_write.go:41-44. Implemented on stdlib hmac —
the token format is standard JWT HS256.
"""

from __future__ import annotations

import base64
import hmac
import hashlib
import json
import time


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def gen_jwt(signing_key: str, fid: str, expires_seconds: int = 10) -> str:
    """Mint a write token bound to one fileId (GenJwt, jwt.go:158-171)."""
    header = {"alg": "HS256", "typ": "JWT"}
    claims = {"exp": int(time.time()) + expires_seconds, "fid": fid}
    seg = _b64(json.dumps(header, separators=(",", ":")).encode()) + "." + \
        _b64(json.dumps(claims, separators=(",", ":")).encode())
    sig = hmac.new(signing_key.encode(), seg.encode(), hashlib.sha256)
    return seg + "." + _b64(sig.digest())


class JwtError(Exception):
    pass


def decode_jwt(signing_key: str, token: str) -> dict:
    """Validate signature + expiry; returns the claims."""
    try:
        head, payload, sig = token.split(".")
    except ValueError:
        raise JwtError("malformed token")
    seg = f"{head}.{payload}"
    want = hmac.new(signing_key.encode(), seg.encode(), hashlib.sha256)
    if not hmac.compare_digest(_b64(want.digest()), sig):
        raise JwtError("bad signature")
    claims = json.loads(_unb64(payload))
    if claims.get("exp", 0) < time.time():
        raise JwtError("expired")
    return claims


def check_write_jwt(signing_key: str, token: str, fid: str) -> None:
    """Raise JwtError unless token authorizes writing fid."""
    claims = decode_jwt(signing_key, token)
    if claims.get("fid") != fid:
        raise JwtError(f"token not valid for fid {fid}")


def get_jwt_from_request(headers, query) -> str:
    """Authorization: Bearer <t> or ?jwt= (GetJwt, jwt.go:173-180)."""
    auth = headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        return auth[7:]
    return query.get("jwt", "")
