"""Mutual-TLS transport config for all inter-server traffic.

Reference: weed/security/tls.go (LoadServerTLS/LoadClientTLS building
credentials from [grpc.<role>] cert/key + [grpc] ca in security.toml,
wired into every gRPC server/client) — here applied to the aiohttp
HTTP/1.1+SSE mesh instead of gRPC.

Process-global by design, like the reference's viper-loaded config: one
`configure()` (or `configure_from_toml()`) call at process start flips
every server listener to TLS-with-client-auth and every client session to
presenting its certificate; `url()` is the single place the scheme is
chosen, so call sites never hardcode http vs https.
"""

from __future__ import annotations

import ssl

import aiohttp

_server_ctx: ssl.SSLContext | None = None
_client_ctx: ssl.SSLContext | None = None


def configure(ca: str, cert: str, key: str,
              require_client_cert: bool = True) -> None:
    """Enable mTLS: every peer presents `cert` signed by `ca`."""
    global _server_ctx, _client_ctx
    sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    sctx.load_cert_chain(cert, key)
    sctx.load_verify_locations(ca)
    if require_client_cert:
        sctx.verify_mode = ssl.CERT_REQUIRED
    cctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    cctx.load_cert_chain(cert, key)
    cctx.load_verify_locations(ca)
    # inter-server certs are issued to service roles, not hostnames
    # (the reference dials by ip:port with a role cert the same way)
    cctx.check_hostname = False
    _server_ctx = sctx
    _client_ctx = cctx


def configure_from_toml(path: str, cfg: dict | None = None) -> bool:
    """Apply the [tls] section of a security.toml (pass cfg when the
    file is already parsed); returns True if TLS was enabled.
    Absent/empty section leaves plaintext HTTP."""
    if cfg is None:
        from ..util.toml import tomllib
        if tomllib is None:
            raise SystemExit(
                "security.toml given but no TOML parser available "
                "(tomllib needs Python 3.11+, or install tomli)")
        with open(path, "rb") as f:
            cfg = tomllib.load(f)
    tls = cfg.get("tls", {})
    if not (tls.get("cert") or tls.get("ca") or tls.get("key")):
        return False
    missing = [k for k in ("ca", "cert", "key") if not tls.get(k)]
    if missing:
        raise SystemExit(
            f"security.toml [tls]: missing {', '.join(missing)} "
            f"(all of ca/cert/key are required to enable mTLS)")
    configure(tls["ca"], tls["cert"], tls["key"],
              require_client_cert=bool(tls.get("require_client_cert",
                                               True)))
    return True


def reset() -> None:
    global _server_ctx, _client_ctx
    _server_ctx = None
    _client_ctx = None


def enabled() -> bool:
    return _server_ctx is not None


def scheme() -> str:
    return "https" if enabled() else "http"


def url(hostport: str, path: str = "") -> str:
    return f"{scheme()}://{hostport}{path}"


def server_ctx() -> ssl.SSLContext | None:
    return _server_ctx


def client_ctx() -> ssl.SSLContext | None:
    """For non-aiohttp clients (urllib in executor threads)."""
    return _client_ctx


def client_connector() -> aiohttp.TCPConnector | None:
    """Connector presenting this process's client certificate; None in
    plaintext mode (aiohttp default connector)."""
    if _client_ctx is None:
        return None
    return aiohttp.TCPConnector(ssl=_client_ctx)


def make_session(**kwargs) -> aiohttp.ClientSession:
    """The one constructor for inter-server sessions: attaches the mTLS
    connector when enabled."""
    conn = client_connector()
    if conn is not None:
        kwargs.setdefault("connector", conn)
    return aiohttp.ClientSession(**kwargs)
