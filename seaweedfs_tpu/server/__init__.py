"""server subpackage."""
