"""EC shard-location cache with staleness tiers.

Reference: weed/storage/store_ec.go:218-259 (cachedLookupEcShardLocations)
— the volume server caches vid -> shard locations so a burst of degraded
reads costs ONE master lookup, not one per interval fetch. Three windows:

  FRESH_S  (11s): after any lookup attempt (success or failure), no new
           lookup is issued for the same vid — a reconstruction storm
           cannot hammer the master.
  TTL_S    (7m): a successful result is served without re-lookup.
  EXPIRE_S (37m): on lookup failure, the last known locations keep being
           served (stale-while-error) until this age, then drop.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class _Entry:
    # time.monotonic() can legitimately be near 0.0 right after boot, so
    # every "never happened" sentinel is -inf-ish, not 0.0
    locations: dict | None = None   # {"shard_id_str": [urls]}
    fetched_at: float = -1e9        # last SUCCESSFUL lookup
    attempted_at: float = -1e9      # last lookup attempt of any outcome
    last_forced: float = -1e9       # last invalidate() that forced a lookup
    stale: bool = False             # invalidated: re-lookup when allowed
    lock: threading.Lock = field(default_factory=threading.Lock)


class EcLocationCache:
    FRESH_S = 11.0
    TTL_S = 7 * 60.0
    EXPIRE_S = 37 * 60.0

    def __init__(self, lookup: Callable[[int], dict | None],
                 now: Callable[[], float] = time.monotonic):
        self._lookup = lookup
        self._now = now
        self._entries: dict[int, _Entry] = {}
        self._lock = threading.Lock()

    def _entry(self, vid: int) -> _Entry:
        with self._lock:
            return self._entries.setdefault(vid, _Entry())

    def get(self, vid: int) -> dict | None:
        """Locations for vid, freshly looked up only when the cache says
        so. Called from executor threads; per-vid lock keeps a storm down
        to one in-flight lookup."""
        e = self._entry(vid)
        now = self._now()
        with e.lock:
            if (e.locations is not None and not e.stale
                    and now - e.fetched_at < self.TTL_S):
                return e.locations
            if now - e.attempted_at < self.FRESH_S:
                # a lookup just happened (maybe by another reader):
                # serve whatever we have rather than dialing again
                return self._stale_or_none(e, now)
            e.attempted_at = now
            locs = None
            try:
                locs = self._lookup(vid)
            except Exception:  # noqa: BLE001 — treated as lookup failure
                locs = None
            if locs is not None:
                e.locations = locs
                e.fetched_at = now
                e.stale = False
                return locs
            return self._stale_or_none(e, now)

    def _stale_or_none(self, e: _Entry, now: float) -> dict | None:
        if e.locations is not None and now - e.fetched_at < self.EXPIRE_S:
            return e.locations
        e.locations = None
        return None

    def peek(self, vid: int) -> dict | None:
        """Whatever locations are cached RIGHT NOW, with no lookup and
        no staleness bookkeeping — the repair planner's holder-grouping
        input (a plan built from slightly stale holders still fetches
        correct bytes; the fetchers re-resolve on failure)."""
        e = self._entries.get(vid)
        return e.locations if e is not None else None

    def invalidate(self, vid: int) -> bool:
        """A shard fetch against cached locations failed: the topology
        has moved under us. The FIRST invalidation in a FRESH_S window
        forces an immediate re-lookup (a degraded read right after a
        shard move must not stay stuck on dead holders); further
        invalidations inside the window fall back to the normal
        suppression, so an every-holder-down storm still costs at most
        one master lookup per FRESH_S. Returns whether this call
        forced the immediate re-lookup (informational)."""
        e = self._entry(vid)
        now = self._now()
        with e.lock:
            e.stale = True  # next allowed get() re-resolves; until then
            #                 the existing map keeps serving by real age
            if now - e.last_forced >= self.FRESH_S:
                e.attempted_at = -1e9
                e.last_forced = now
                # journal the forced refresh (rate-bounded to one per
                # FRESH_S per vid by construction): a degraded-read
                # burst chasing a moved holder map is core evidence for
                # a latency violation window
                from ..util import events
                events.record("holder_refresh", vid=vid)
                return True
            return False
