"""Hand-rolled HTTP/1.1 fast path for the needle data plane.

Reference: the reference serves its public needle API straight off Go's
net/http (volume_server_handlers_read.go:30-140,
volume_server_handlers_write.go:19-73) and its published benchmark
(README.md:463-495) is set by per-request HTTP cost, not by the O(1)
needle engine. BENCH_NEEDLE.md measured the same here: the engine does
54k reads/s isolated while aiohttp's parse+route+response machinery
caps the served rate at ~3.8k/s on this single core.

This module is a raw `asyncio.Protocol` that parses just enough HTTP
for the two hot shapes — `GET /<vid>,<fid>` and `POST/PUT /<vid>,<fid>`
with a raw body — and answers them with preformatted header bytes.
EVERYTHING else (cold routes, conditional headers, multipart, chunked
manifests, gzip, JWT, replication fan-out, redirects, resize) is handed
to the full aiohttp application by swapping the connection's protocol
in place (`transport.set_protocol`), so those requests keep byte-for-
byte the semantics of the existing handlers; the swap preserves the
real peer address, so IP guards keep working. A connection that leaves
the fast path stays on aiohttp for its lifetime — per-connection state
stays trivially simple and benchmark/data-plane connections never pay
for it.
"""

from __future__ import annotations

import asyncio
import json
import re
import time

from ..storage import types as t
from ..storage.backend import BackendError
from ..storage.needle import (FLAG_HAS_LAST_MODIFIED, CrcMismatch, Needle,
                              NeedleError)
from ..storage.volume import AlreadyDeleted, NotFound, VolumeError
from ..ec.ec_volume import EcVolumeError
from ..util import tracing
from ..util.failpoints import (FailpointDrop, FailpointError,
                               pending as _fp_pending)

# context-propagating executor hop (store spans parent correctly)
_traced_executor = tracing.run_in_executor

_REQ_LINE = re.compile(
    rb"^(GET|POST|PUT) /(\d+,[0-9a-fA-F]+)((?:\?[^ ]*)?) HTTP/1\.1$")

# preformatted cold responses
_R404 = (b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n\r\n")
_R404_VOL = (b"HTTP/1.1 404 Not Found\r\n"
             b"Content-Type: application/json; charset=utf-8\r\n"
             b"Content-Length: 22\r\n\r\n{\"error\": \"not found\"}")
_R401_BODY = b"{\"error\": \"ip not in whitelist\"}"
# built from len(): a hand-counted Content-Length that disagrees with
# the body desyncs every spec-conformant keep-alive client
_R401_IP = (b"HTTP/1.1 401 Unauthorized\r\n"
            b"Content-Type: application/json; charset=utf-8\r\n"
            b"Content-Length: " + str(len(_R401_BODY)).encode()
            + b"\r\n\r\n" + _R401_BODY)
_R400 = (b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n")

# tiny cache of formatted Last-Modified values: needles written in the
# same second share the string, and strftime is the priciest call left
# on the read path
_LM_CACHE: dict[int, bytes] = {}


def _http_date(ts: int) -> bytes:
    v = _LM_CACHE.get(ts)
    if v is None:
        v = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                          time.gmtime(ts)).encode()
        if len(_LM_CACHE) > 64:
            _LM_CACHE.clear()
        _LM_CACHE[ts] = v
    return v


def _json_err(status: int, reason: str, msg: str) -> bytes:
    body = json.dumps({"error": msg}).encode()
    return (b"HTTP/1.1 %d %s\r\n"
            b"Content-Type: application/json; charset=utf-8\r\n"
            b"Content-Length: %d\r\n\r\n"
            % (status, reason.encode(), len(body))) + body


class FastNeedleProtocol(asyncio.Protocol):
    """Per-connection fast parser; upgrades to aiohttp on anything cold."""

    __slots__ = ("vs", "buf", "transport", "peer_ip", "_busy", "_closed",
                 "_task")

    def __init__(self, vs) -> None:
        self.vs = vs
        self.buf = bytearray()
        self.transport = None
        self.peer_ip: str | None = None
        self._busy = False        # an async handler owns the buffer head
        self._closed = False
        self._task: asyncio.Task | None = None

    # -- asyncio.Protocol --

    def connection_made(self, transport) -> None:
        self.transport = transport
        if not hasattr(self.vs, "_fast_conns"):
            self.vs._fast_conns = set()
        self.vs._fast_conns.add(transport)
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if peer else None
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                import socket as _s
                sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
            except OSError:
                pass

    def connection_lost(self, exc) -> None:
        self._closed = True
        getattr(self.vs, "_fast_conns", set()).discard(self.transport)

    def data_received(self, data: bytes) -> None:
        self.buf += data
        if not self._busy:
            self._pump()

    # -- request pump --

    def _pump(self) -> None:
        """Handle complete fast requests at the head of the buffer;
        upgrade the connection on the first cold one."""
        while not self._closed:
            head_end = self.buf.find(b"\r\n\r\n")
            if head_end < 0:
                if len(self.buf) > 32 * 1024:
                    self._upgrade()      # oversized header block: not ours
                return
            line_end = self.buf.find(b"\r\n")
            m = _REQ_LINE.match(bytes(self.buf[:line_end]))
            if m is None:
                self._upgrade()
                return
            headers = self._parse_headers(head_end, line_end)
            if headers is None:
                self._upgrade()
                return
            method = m.group(1)
            if method == b"GET":
                if m.group(3) not in (b"", b"?") or (
                        headers.keys() & {"range", "if-none-match",
                                          "if-modified-since", "etag-md5"}):
                    self._upgrade()
                    return
                fid_s = m.group(2).decode()
                del self.buf[:head_end + 4]
                self._spawn(self._do_get(fid_s, headers))
                return
            # POST/PUT
            if not self._write_is_fast(m, headers):
                self._upgrade()
                return
            clen = int(headers.get("content-length", "0"))
            total = head_end + 4 + clen
            if len(self.buf) < total:
                return               # body still in flight
            body = bytes(self.buf[head_end + 4:total])
            fid_s = m.group(2).decode()
            del self.buf[:total]
            self._spawn(self._do_post(fid_s, m.group(3), headers, body))
            return

    def _spawn(self, coro) -> None:
        """Run an async handler for the request at the buffer head.
        The task handle is retained (an unreferenced asyncio task may be
        garbage-collected mid-flight) and a done-callback closes the
        connection if the handler died before answering — otherwise
        `_busy` would stay set and the connection would wedge silently."""
        self._busy = True
        self._task = asyncio.get_running_loop().create_task(coro)
        self._task.add_done_callback(self._handler_done)

    def _handler_done(self, task: asyncio.Task) -> None:
        if self._task is task:
            # _finish -> _pump may already have spawned the NEXT
            # request's task; never clobber that newer reference
            self._task = None
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None and not self._closed:
            # no response was written for the consumed request: the
            # stream is desynced, closing is the only coherent answer
            self._closed = True
            self._busy = False
            self.transport.close()

    def _parse_headers(self, head_end: int, line_end: int
                       ) -> dict[str, str] | None:
        """Lower-cased header dict, or None when the block needs the
        full parser (duplicates, continuations, anything malformed)."""
        headers: dict[str, str] = {}
        block = bytes(self.buf[line_end + 2:head_end])
        if not block:
            return headers
        for raw in block.split(b"\r\n"):
            i = raw.find(b":")
            if i <= 0 or raw[:1] in (b" ", b"\t"):
                return None
            try:
                k = raw[:i].decode("ascii").lower()
                if k in headers:
                    return None   # duplicate headers: full parser's job
                headers[k] = raw[i + 1:].strip().decode("latin-1")
            except UnicodeDecodeError:
                return None
        return headers

    def _write_is_fast(self, m, headers: dict[str, str]) -> bool:
        vs = self.vs
        if vs.jwt_key:
            return False             # token checks stay with aiohttp
        q = m.group(3)
        if q not in (b"", b"?"):
            # only ts/ttl are understood here; cm/type/etc go cold
            for kv in q[1:].split(b"&"):
                if kv and kv.split(b"=")[0] not in (b"ts", b"ttl"):
                    return False
        if "transfer-encoding" in headers or "expect" in headers:
            return False
        clen = headers.get("content-length")
        if clen is None or not clen.isdigit() or int(clen) > (4 << 20):
            return False
        ctype = headers.get("content-type", "")
        if ctype.startswith("multipart/") or ctype.startswith("image/jp"):
            return False             # multipart parse / EXIF fix: cold
        if "x-raw-needle" in headers:
            return False             # replica write framing: cold
        for k in headers:
            if k.startswith("seaweed-"):
                return False         # pair headers: cold
        return True

    # -- fast handlers --

    async def _do_get(self, fid_s: str, headers: dict[str, str]) -> None:
        vs = self.vs
        out: bytes
        body = b""
        try:
            fid = t.FileId.parse(fid_s)
        except ValueError as e:
            self._finish(_json_err(400, "Bad Request", str(e)))
            return
        wc = vs.worker_ctx
        if wc is not None and not wc.owns(fid.volume_id):
            # a sibling worker's partition: replay through aiohttp,
            # whose worker-routing middleware proxies to the owner
            self._upgrade_replay(b"GET", fid_s, headers)
            return
        if not vs.store.has_volume(fid.volume_id):
            if vs.read_redirect:
                self._upgrade_replay(b"GET", fid_s, headers)
                return
            self._finish(_R404_VOL)
            return
        # volume-tier entry span for the fast path; a request that
        # replays into aiohttp cancels it (the full handler's
        # middleware records its own, joined to the same traceparent)
        sp = tracing.start_root("volume", "read", headers=headers)
        with sp:
            # hot-needle cache peek first: a hit answers on the event
            # loop with zero disk I/O and no executor round-trip — the
            # dominant per-request cost left on this path
            # (BENCH_NEEDLE.md). count=False: whether this lookup
            # counts depends on what the needle turns out to be — a
            # pairs/gzip/manifest needle replays through aiohttp,
            # which does its own (single) accounting
            n = vs.store.cached_needle(fid.volume_id, fid.key,
                                       fid.cookie, count=False)
            from_cache = n is not None
            try:
                if n is None:
                    n = await _traced_executor(
                        vs.store.read_needle,
                        fid.volume_id, fid.key, fid.cookie)
            except (NotFound, AlreadyDeleted):
                vs.count("read", "404")
                sp.status = "404"
                self._finish(_R404)
                return
            except CrcMismatch as e:
                sp.status = "500"
                self._finish(_json_err(500, "Internal Server Error",
                                       str(e)))
                return
            except (EcVolumeError, BackendError) as e:
                vs.count("read", "error")
                sp.status = "503"
                self._finish(_json_err(503, "Service Unavailable",
                                       str(e)))
                return
            except FailpointDrop:
                # injected connection drop: sever, don't answer
                sp.status = "drop"
                self._closed = True
                self._busy = False
                self.transport.close()
                return
            except FailpointError as e:
                sp.status = str(e.status)
                self._finish(_json_err(e.status, "Injected Error",
                                       str(e)))
                return
            except Exception as e:  # noqa: BLE001 — keep conn coherent
                sp.status = "500"
                self._finish(_json_err(500, "Internal Server Error",
                                       str(e)))
                return
            if n.pairs or n.is_chunked_manifest or n.is_gzipped:
                # pairs->headers / manifest assembly / gzip negotiation:
                # re-serve this request through the full handler (which
                # counts the cache hit/miss for this request itself)
                sp.cancel()
                self._upgrade_replay(b"GET", fid_s, headers)
                return
            if from_cache:
                # deferred accounting for the served fast-path hit
                vs.store.needle_cache.hit(n)
                sp.set("source", "cache")
            vs.count("read", "ok")
            sp.nbytes = len(n.data)
            body = n.data
        ct = n.mime.decode() if n.mime else "application/octet-stream"
        extra = b""
        if n.name:
            from .volume_server import _guess_mime
            fname = n.name.decode(errors="replace")
            if not n.mime:
                ct = _guess_mime(fname, ct)
            fname = "".join(c for c in fname if c >= " ")
            esc = fname.replace("\\", "\\\\").replace('"', '\\"')
            extra += (b"Content-Disposition: inline; filename=\""
                      + esc.encode() + b"\"\r\n")
        if n.last_modified:
            extra += (b"Last-Modified: " + _http_date(int(n.last_modified))
                      + b"\r\n")
        out = (b"HTTP/1.1 200 OK\r\nContent-Type: " + ct.encode()
               + b"\r\nContent-Length: " + str(len(body)).encode()
               + b"\r\nEtag: \"" + n.etag().encode()
               + b"\"\r\nAccept-Ranges: bytes\r\n" + extra + b"\r\n")
        if len(body) < 64 * 1024:
            self._finish(out + body)       # one syscall for small reads
        else:
            self._finish(out, body)

    async def _do_post(self, fid_s: str, q: bytes,
                       headers: dict[str, str], body: bytes) -> None:
        vs = self.vs
        wc = vs.worker_ctx
        # an intra-host worker hop carries the launch token: the entry
        # worker already ran the guard against the real client IP
        proxied_hop = wc is not None and \
            wc.token_ok(headers.get("x-swtpu-worker"))
        if not proxied_hop and not vs.guard.empty \
                and not vs.guard.allows(self.peer_ip):
            self._finish(_R401_IP)
            return
        try:
            fid = t.FileId.parse(fid_s)
        except ValueError as e:
            self._finish(_json_err(400, "Bad Request", str(e)))
            return
        if wc is not None and not wc.owns(fid.volume_id):
            self._upgrade_replay(b"POST", fid_s, headers, q, body)
            return
        # replication fan-out stays with aiohttp: decide BEFORE writing
        v = vs.store.volumes.get(fid.volume_id)
        if v is not None:
            rp = v.super_block.replica_placement
            if rp and rp.copy_count > 1:
                self._upgrade_replay(b"POST", fid_s, headers, q, body)
                return
        ts_s = ttl_s = ""
        if q not in (b"", b"?"):
            for kv in q[1:].split(b"&"):
                k, _, val = kv.partition(b"=")
                if k == b"ts":
                    ts_s = val.decode()
                elif k == b"ttl":
                    ttl_s = val.decode()
        ctype = headers.get("content-type", "")
        mime = b""
        if ctype and ctype != "application/octet-stream":
            mime = ctype.split(";")[0].encode()
        try:
            last_modified = int(ts_s or time.time())
        except ValueError:
            last_modified = int(time.time())
        if not 0 <= last_modified < (1 << 40):
            last_modified = int(time.time())
        try:
            n = Needle(cookie=fid.cookie, id=fid.key, data=body, mime=mime,
                       ttl=t.TTL.parse(ttl_s), last_modified=last_modified)
        except (NeedleError, ValueError) as e:
            self._finish(_json_err(400, "Bad Request", str(e)))
            return
        n.set_flag(FLAG_HAS_LAST_MODIFIED)
        with tracing.start_root("volume", "write", headers=headers) as sp:
            try:
                _, size = await _traced_executor(
                    vs.store.write_needle, fid.volume_id, n)
            except NotFound:
                sp.status = "404"
                self._finish(_json_err(404, "Not Found",
                                       "volume not found"))
                return
            except NeedleError as e:
                sp.status = "400"
                self._finish(_json_err(400, "Bad Request", str(e)))
                return
            except VolumeError as e:
                sp.status = "409"
                self._finish(_json_err(409, "Conflict", str(e)))
                return
            except FailpointDrop:
                sp.status = "drop"
                self._closed = True
                self._busy = False
                self.transport.close()
                return
            except FailpointError as e:
                sp.status = str(e.status)
                self._finish(_json_err(e.status, "Injected Error",
                                       str(e)))
                return
            except Exception as e:  # noqa: BLE001
                sp.status = "500"
                self._finish(_json_err(500, "Internal Server Error",
                                       str(e)))
                return
            sp.nbytes = len(body)
        vs.count("write", "ok")
        rbody = (b"{\"name\": \"\", \"size\": " + str(size).encode()
                 + b", \"eTag\": \"" + n.etag().encode() + b"\"}")
        self._finish(b"HTTP/1.1 201 Created\r\n"
                     b"Content-Type: application/json; charset=utf-8\r\n"
                     b"Content-Length: " + str(len(rbody)).encode()
                     + b"\r\n\r\n" + rbody)

    # -- plumbing --

    def _finish(self, out: bytes, body: bytes = b"") -> None:
        if not self._closed:
            self.transport.write(out)
            if body:
                self.transport.write(body)
        self._busy = False
        if self.buf and not self._closed:
            self._pump()

    def _upgrade(self) -> None:
        """Swap this connection onto the full aiohttp protocol, replaying
        any buffered bytes. Keeps the real transport (and so the real
        peer IP) — this is the in-process websocket-upgrade pattern, not
        a proxy hop."""
        proto = self.vs._runner.server()
        raw = bytes(self.buf)
        self.buf.clear()
        self._closed = True          # this protocol is done
        getattr(self.vs, "_fast_conns", set()).discard(self.transport)
        self.transport.set_protocol(proto)
        proto.connection_made(self.transport)
        if raw:
            proto.data_received(raw)

    def _upgrade_replay(self, method: bytes, fid_s: str,
                        headers: dict[str, str], q: bytes = b"",
                        body: bytes = b"") -> None:
        """Upgrade when the fast path discovered mid-request that the
        full handler must serve it: reconstruct the consumed request at
        the FRONT of the buffer, then upgrade."""
        hdr_blob = b"".join(
            k.title().encode() + b": " + v.encode("latin-1") + b"\r\n"
            for k, v in headers.items())
        req = (method + b" /" + fid_s.encode() + q + b" HTTP/1.1\r\n"
               + hdr_blob + b"\r\n" + body)
        self.buf[:0] = req
        self._upgrade()


class FastAssignProtocol(asyncio.Protocol):
    """Master-side fast path for `GET /dir/assign` — the other half of
    every data-plane write (the reference answers it from an in-memory
    VolumeLayout pick + sequencer bump, master_server_handlers.go:60-99;
    that is exactly what runs here, with no HTTP framework between the
    socket and the pick). Leader-less, growth-needing, guarded-rejected
    and every non-assign request upgrade to the aiohttp app unchanged.

    The whole decision is synchronous, so a cold request is detected
    BEFORE any state changes and the original bytes simply stay in the
    buffer for aiohttp — no replay reconstruction needed."""

    _RE = re.compile(rb"^GET /dir/assign((?:\?[^ ]*)?) HTTP/1\.1$")

    __slots__ = ("ms", "buf", "transport", "peer_ip", "_closed")

    def __init__(self, ms) -> None:
        self.ms = ms
        self.buf = bytearray()
        self.transport = None
        self.peer_ip: str | None = None
        self._closed = False

    def connection_made(self, transport) -> None:
        self.transport = transport
        if not hasattr(self.ms, "_fast_conns"):
            self.ms._fast_conns = set()
        self.ms._fast_conns.add(transport)
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if peer else None

    def connection_lost(self, exc) -> None:
        self._closed = True
        getattr(self.ms, "_fast_conns", set()).discard(self.transport)

    def data_received(self, data: bytes) -> None:
        self.buf += data
        while not self._closed:
            head_end = self.buf.find(b"\r\n\r\n")
            if head_end < 0:
                if len(self.buf) > 32 * 1024:
                    self._upgrade()
                return
            m = self._RE.match(bytes(self.buf[:self.buf.find(b"\r\n")]))
            if m is None:
                self._upgrade()
                return
            out = self._assign(m.group(1))
            if out is None:
                self._upgrade()     # cold: bytes stay buffered
                return
            del self.buf[:head_end + 4]
            self.transport.write(out)

    def _assign(self, q: bytes) -> bytes | None:
        """Synchronous assign; None => let aiohttp handle it."""
        ms = self.ms
        if _fp_pending("master.assign"):
            return None             # armed failpoint: full handler fires it
        if not ms.is_leader:
            return None             # leader proxy path
        count_s = collection = replication = ttl = b""
        if q not in (b"", b"?"):
            for kv in q[1:].split(b"&"):
                k, _, val = kv.partition(b"=")
                if k == b"count":
                    count_s = val
                elif k == b"collection":
                    collection = val
                elif k == b"replication":
                    replication = val
                elif k == b"ttl":
                    ttl = val
                elif k not in (b"dataCenter", b""):
                    return None     # unknown knob: full handler decides
                elif k == b"dataCenter" and val:
                    return None     # dc-constrained growth: cold
        if b"%" in q or b"+" in q:
            return None             # urlencoded values: full parser
        if not ms.guard.empty and not ms.guard.allows(self.peer_ip):
            return _R401_IP
        try:
            count = int(count_s or 1)
        except ValueError:
            return None
        coll = collection.decode()
        repl = replication.decode() or ms.default_replication
        ttl_s = ttl.decode()
        try:
            from ..storage.super_block import ReplicaPlacement
            rp = ReplicaPlacement.parse(repl)
        except ValueError as e:
            return _json_err(400, "Bad Request", str(e))
        lay = ms._layout(coll, repl, ttl_s)
        vid = lay.pick_for_write(ms.topo, rp.copy_count)
        if vid is None:
            return None             # growth: serialized in aiohttp
        ms.count_assign()
        key = ms.seq.next_file_id(count)
        fid = str(t.FileId(vid, key, t.random_cookie()))
        node = ms.topo.lookup(vid)[0]
        out = {"fid": fid, "url": node.url, "publicUrl": node.public_url,
               "count": count}
        if ms.jwt_key:
            from ..security.jwt import gen_jwt
            out["auth"] = gen_jwt(ms.jwt_key, fid)
        body = json.dumps(out).encode()
        return (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json; charset=utf-8\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    def _upgrade(self) -> None:
        proto = self.ms._runner.server()
        raw = bytes(self.buf)
        self.buf.clear()
        self._closed = True
        getattr(self.ms, "_fast_conns", set()).discard(self.transport)
        self.transport.set_protocol(proto)
        proto.connection_made(self.transport)
        if raw:
            proto.data_received(raw)


class AcceleratorAssignProtocol(FastAssignProtocol):
    """Raw listener of a master assign-accelerator worker
    (server/workers.py AssignAccelerator): identical wire discipline to
    FastAssignProtocol (the `ms` slot holds the accelerator, which
    exposes the same `_runner`/`_fast_conns` surface), but the assign
    decision comes from the accelerator's leased ids + writable-set
    snapshot instead of the live topology, and a cold request upgrades
    onto the accelerator's transparent proxy app."""

    __slots__ = ()

    def _assign(self, q: bytes) -> bytes | None:
        return self.ms.fast_assign(q, self.peer_ip)
