"""Hand-rolled HTTP/1.1 fast path for the needle data plane.

Reference: the reference serves its public needle API straight off Go's
net/http (volume_server_handlers_read.go:30-140,
volume_server_handlers_write.go:19-73) and its published benchmark
(README.md:463-495) is set by per-request HTTP cost, not by the O(1)
needle engine. BENCH_NEEDLE.md measured the same here: the engine does
54k reads/s isolated while aiohttp's parse+route+response machinery
caps the served rate at ~3.8k/s on this single core.

This module is a raw `asyncio.Protocol` that parses just enough HTTP
for the hot shapes — `GET/POST/PUT/DELETE /<vid>,<fid>` and the
multi-needle `GET /batch?fids=...` — and hands them to the UNIFIED wire
layer (server/wire.py), the same parse/handle/respond code the aiohttp
listener uses, then renders the WireResponse as preformatted bytes.
Cold needle bodies go disk->socket with `loop.sendfile` (zero-copy;
`source=sendfile` in the trace). EVERYTHING the shared layer marks
`upgrade` (chunked-manifest assembly, multipart, JWT'd writes,
sibling-owned volumes) is handed to the full aiohttp application by
swapping the connection's protocol in place (`transport.set_protocol`),
so those requests keep byte-for-byte the semantics of the full
handlers; the swap preserves the real peer address, so IP guards keep
working. A connection that leaves the fast path stays on aiohttp for
its lifetime.
"""

from __future__ import annotations

import asyncio
import json
import re

from ..master.sequence import SequenceBehind
from ..util import tracing
from ..util.failpoints import pending as _fp_pending
from ..util.frame import MAGIC as _FRAME_MAGIC
from . import wire

_REQ_LINE = re.compile(
    rb"^(GET|POST|PUT|DELETE) /(\d+,[0-9a-fA-F]+)"
    rb"((?:\?[^ ]*)?) HTTP/1\.1$")
_BATCH_LINE = re.compile(rb"^GET /batch((?:\?[^ ]*)?) HTTP/1\.1$")

_R401_BODY = b"{\"error\": \"ip not in whitelist\"}"
# built from len(): a hand-counted Content-Length that disagrees with
# the body desyncs every spec-conformant keep-alive client
_R401_IP = (b"HTTP/1.1 401 Unauthorized\r\n"
            b"Content-Type: application/json; charset=utf-8\r\n"
            b"Content-Length: " + str(len(_R401_BODY)).encode()
            + b"\r\n\r\n" + _R401_BODY)


def _json_err(status: int, reason: str, msg: str) -> bytes:
    body = json.dumps({"error": msg}).encode()
    return (b"HTTP/1.1 %d %s\r\n"
            b"Content-Type: application/json; charset=utf-8\r\n"
            b"Content-Length: %d\r\n\r\n"
            % (status, reason.encode(), len(body))) + body


def _parse_query(q: bytes) -> dict | None:
    """Simple query bytes -> dict; None when the full parser must take
    over (%-escapes, '+' spaces)."""
    if q in (b"", b"?"):
        return {}
    if b"%" in q or b"+" in q:
        return None
    out: dict = {}
    for kv in q[1:].split(b"&"):
        if not kv:
            continue
        k, _, v = kv.partition(b"=")
        try:
            out[k.decode("ascii")] = v.decode("ascii")
        except UnicodeDecodeError:
            return None
    return out


class FastNeedleProtocol(asyncio.Protocol):
    """Per-connection fast parser; upgrades to aiohttp on anything cold."""

    __slots__ = ("vs", "buf", "transport", "peer_ip", "_busy", "_closed",
                 "_task")

    def __init__(self, vs) -> None:
        self.vs = vs
        self.buf = bytearray()
        self.transport = None
        self.peer_ip: str | None = None
        self._busy = False        # an async handler owns the buffer head
        self._closed = False
        self._task: asyncio.Task | None = None

    # -- asyncio.Protocol --

    def connection_made(self, transport) -> None:
        self.transport = transport
        if not hasattr(self.vs, "_fast_conns"):
            self.vs._fast_conns = set()
        self.vs._fast_conns.add(transport)
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if peer else None
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                import socket as _s
                sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
            except OSError:
                pass

    def connection_lost(self, exc) -> None:
        self._closed = True
        getattr(self.vs, "_fast_conns", set()).discard(self.transport)

    def data_received(self, data: bytes) -> None:
        self.buf += data
        if not self._busy:
            self._pump()

    # -- request pump --

    def _pump(self) -> None:
        """Handle complete fast requests at the head of the buffer;
        upgrade the connection on the first cold one."""
        while not self._closed:
            if self.buf[:1] == _FRAME_MAGIC[:1]:
                # binary frame preamble (util/frame.py): no HTTP method
                # starts with this byte, so the connection is either a
                # frame client or garbage — swap protocols in place
                # once the magic is complete (frameserver drops
                # mismatches with GOAWAY via its decoder)
                if self.buf.startswith(_FRAME_MAGIC):
                    self._upgrade_frames()
                    return
                if len(self.buf) < len(_FRAME_MAGIC) and \
                        _FRAME_MAGIC.startswith(bytes(self.buf)):
                    return            # preamble still arriving
                self._upgrade()       # same first byte, not the magic:
                return                # let the full parser answer
            head_end = self.buf.find(b"\r\n\r\n")
            if head_end < 0:
                if len(self.buf) > 32 * 1024:
                    self._upgrade()      # oversized header block: not ours
                return
            line_end = self.buf.find(b"\r\n")
            req_line = bytes(self.buf[:line_end])
            m = _REQ_LINE.match(req_line)
            bm = None if m else _BATCH_LINE.match(req_line)
            if m is None and bm is None:
                self._upgrade()
                return
            headers = self._parse_headers(head_end, line_end)
            if headers is None:
                self._upgrade()
                return
            if bm is not None:
                query = _parse_query(bm.group(1))
                if query is None:
                    self._upgrade()
                    return
                del self.buf[:head_end + 4]
                self._spawn(self._do_batch(query, headers))
                return
            method = m.group(1)
            query = _parse_query(m.group(3))
            if query is None:
                self._upgrade()
                return
            fid_s = m.group(2).decode()
            if method == b"GET":
                del self.buf[:head_end + 4]
                self._spawn(self._do_get(fid_s, m.group(3), headers,
                                         query))
                return
            if method == b"DELETE":
                if headers.get("content-length", "0") != "0" \
                        or "transfer-encoding" in headers:
                    self._upgrade()  # bodied DELETE: full parser's job
                    return
                del self.buf[:head_end + 4]
                self._spawn(self._do_delete(fid_s, m.group(3), headers,
                                            query))
                return
            # POST/PUT
            if not self._write_is_fast(headers):
                self._upgrade()
                return
            clen = int(headers.get("content-length", "0"))
            total = head_end + 4 + clen
            if len(self.buf) < total:
                return               # body still in flight
            body = bytes(self.buf[head_end + 4:total])
            del self.buf[:total]
            self._spawn(self._do_post(fid_s, m.group(3), headers, query,
                                      body))
            return

    def _spawn(self, coro) -> None:
        """Run an async handler for the request at the buffer head.
        The task handle is retained (an unreferenced asyncio task may be
        garbage-collected mid-flight) and a done-callback closes the
        connection if the handler died before answering — otherwise
        `_busy` would stay set and the connection would wedge silently."""
        self._busy = True
        self._task = asyncio.get_running_loop().create_task(coro)
        self._task.add_done_callback(self._handler_done)

    def _handler_done(self, task: asyncio.Task) -> None:
        if self._task is task:
            # _finish -> _pump may already have spawned the NEXT
            # request's task; never clobber that newer reference
            self._task = None
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None and not self._closed:
            # no response was written for the consumed request: the
            # stream is desynced, closing is the only coherent answer
            self._closed = True
            self._busy = False
            self.transport.close()

    def _parse_headers(self, head_end: int, line_end: int
                       ) -> dict | None:
        """Lower-cased header dict, or None when the block needs the
        full parser (duplicates, continuations, anything malformed)."""
        headers: dict = {}
        block = bytes(self.buf[line_end + 2:head_end])
        if not block:
            return headers
        for raw in block.split(b"\r\n"):
            i = raw.find(b":")
            if i <= 0 or raw[:1] in (b" ", b"\t"):
                return None
            try:
                k = raw[:i].decode("ascii").lower()
                if k in headers:
                    return None   # duplicate headers: full parser's job
                headers[k] = raw[i + 1:].strip().decode("latin-1")
            except UnicodeDecodeError:
                return None
        return headers

    def _write_is_fast(self, headers: dict) -> bool:
        """Writes the shared wire layer can take straight from a raw
        body; the rest (JWT checks, multipart parse, replica-framed
        bodies, chunked uploads) go to the full aiohttp handler."""
        vs = self.vs
        if vs.jwt_key:
            return False             # token checks stay with aiohttp
        if "transfer-encoding" in headers or "expect" in headers:
            return False
        clen = headers.get("content-length")
        if clen is None or not clen.isdigit() or int(clen) > (4 << 20):
            return False
        if headers.get("content-type", "").startswith("multipart/"):
            return False             # multipart parse: cold
        if "x-raw-needle" in headers:
            return False             # replica write framing: cold
        return True

    def _worker_hop(self, headers: dict) -> bool:
        wc = self.vs.worker_ctx
        return wc is not None and \
            wc.token_ok(headers.get("x-swtpu-worker"))

    def _wire_request(self, method: str, fid_s: str, query: dict,
                      headers: dict, body: bytes | None = None
                      ) -> wire.WireRequest:
        return wire.WireRequest(
            method=method, fid_s=fid_s, query=query, headers=headers,
            peer_ip=self.peer_ip, body=body, raw=True,
            worker_hop=self._worker_hop(headers))

    # -- fast handlers (adapters over server/wire.py) --

    async def _do_get(self, fid_s: str, q: bytes, headers: dict,
                      query: dict) -> None:
        vs = self.vs
        wr = self._wire_request("GET", fid_s, query, headers)
        # volume-tier entry span for the fast path; a request that
        # replays into aiohttp cancels it (the full handler's
        # middleware records its own, joined to the same traceparent)
        sp = tracing.start_root("volume", "read", headers=headers)
        with sp:
            resp = await wire.serve_read(vs, wr)
            if resp.upgrade:
                sp.cancel()
                self._upgrade_replay(b"GET", fid_s, headers, q)
                return
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            await self._respond(resp)

    async def _do_post(self, fid_s: str, q: bytes, headers: dict,
                       query: dict, body: bytes) -> None:
        vs = self.vs
        wr = self._wire_request("POST", fid_s, query, headers, body)
        # an intra-host worker hop carries the launch token: the entry
        # worker already ran the guard against the real client IP
        if not wr.worker_hop and not vs.guard.empty \
                and not vs.guard.allows(self.peer_ip):
            self._finish(_R401_IP)
            return
        wc = vs.worker_ctx
        vid_s = fid_s.split(",", 1)[0]
        if wc is not None and not wr.worker_hop \
                and not wc.owns(int(vid_s)):
            self._upgrade_replay(b"POST", fid_s, headers, q, body)
            return
        with tracing.start_root("volume", "write", headers=headers) as sp:
            resp = await wire.serve_write(vs, wr)
            if resp.upgrade:
                sp.cancel()
                self._upgrade_replay(b"POST", fid_s, headers, q, body)
                return
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            await self._respond(resp)

    async def _do_delete(self, fid_s: str, q: bytes, headers: dict,
                         query: dict) -> None:
        vs = self.vs
        wr = self._wire_request("DELETE", fid_s, query, headers)
        if vs.jwt_key:
            # token checks stay with aiohttp (shared guard exemptions)
            self._upgrade_replay(b"DELETE", fid_s, headers, q)
            return
        if not wr.worker_hop and not vs.guard.empty \
                and not vs.guard.allows(self.peer_ip):
            self._finish(_R401_IP)
            return
        wc = vs.worker_ctx
        vid_s = fid_s.split(",", 1)[0]
        if wc is not None and not wr.worker_hop \
                and not wc.owns(int(vid_s)):
            self._upgrade_replay(b"DELETE", fid_s, headers, q)
            return
        with tracing.start_root("volume", "delete",
                                headers=headers) as sp:
            resp = await wire.serve_delete(vs, wr)
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            await self._respond(resp)

    async def _do_batch(self, query: dict, headers: dict) -> None:
        vs = self.vs
        wr = self._wire_request("GET", "", query, headers)
        with tracing.start_root("volume", "batch",
                                headers=headers) as sp:
            resp = await wire.serve_batch(vs, wr)
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            await self._respond(resp)

    # -- response rendering --

    def _encode_head(self, resp: wire.WireResponse) -> bytes:
        out = [b"HTTP/1.1 %d %s\r\n"
               % (resp.status, wire.reason(resp.status).encode())]
        body_len = (len(resp.body) if resp.truncate_to >= 0
                    else resp.content_length)
        if not resp.head or resp.status not in (301, 304):
            out.append(b"Content-Type: "
                       + resp.content_type.encode() + b"\r\n")
        out.append(b"Content-Length: " + str(body_len).encode()
                   + b"\r\n")
        for k, v in resp.headers.items():
            out.append(k.encode("latin-1") + b": "
                       + str(v).encode("latin-1") + b"\r\n")
        out.append(b"\r\n")
        return b"".join(out)

    async def _respond(self, resp: wire.WireResponse) -> None:
        if resp.drop:
            # injected connection drop: sever, don't answer
            self._closed = True
            self._busy = False
            self.transport.close()
            return
        if resp.truncate_to >= 0:
            # failpoint truncate: full Content-Length, partial body,
            # dead socket — the mid-read death degraded reads survive
            if not self._closed:
                self.transport.write(self._encode_head(resp))
                self.transport.write(resp.body[:resp.truncate_to])
            self._closed = True
            self._busy = False
            self.transport.close()
            return
        if resp.sendfile is not None:
            await self._respond_sendfile(resp)
            return
        head = self._encode_head(resp)
        if resp.head or not resp.body:
            self._finish(head)
        elif len(resp.body) < 64 * 1024:
            self._finish(head + resp.body)  # one syscall for small reads
        else:
            self._finish(head, resp.body)

    async def _respond_sendfile(self, resp: wire.WireResponse) -> None:
        """Zero-copy body: headers via transport.write, then the needle
        data region goes disk->socket with loop.sendfile (kernel copy;
        asyncio falls back to executor-chunked reads where sendfile is
        unavailable, e.g. TLS transports)."""
        ref = resp.sendfile
        try:
            if self._closed:
                return
            self.transport.write(self._encode_head(resp))
            try:
                await asyncio.get_running_loop().sendfile(
                    self.transport, ref.file, ref.offset, ref.length,
                    fallback=True)
            except (OSError, RuntimeError):
                # mid-send failure: the declared Content-Length can no
                # longer be honored — sever so the client sees a short
                # body, exactly like a buffered write tear
                self._closed = True
                self.transport.close()
                return
        finally:
            ref.close()
        self._busy = False
        if self.buf and not self._closed:
            self._pump()

    # -- plumbing --

    def _finish(self, out: bytes, body: bytes = b"") -> None:
        if not self._closed:
            self.transport.write(out)
            if body:
                self.transport.write(body)
        self._busy = False
        if self.buf and not self._closed:
            self._pump()

    def _upgrade(self) -> None:
        """Swap this connection onto the full aiohttp protocol, replaying
        any buffered bytes. Keeps the real transport (and so the real
        peer IP) — this is the in-process websocket-upgrade pattern, not
        a proxy hop."""
        proto = self.vs._runner.server()
        raw = bytes(self.buf)
        self.buf.clear()
        self._closed = True          # this protocol is done
        getattr(self.vs, "_fast_conns", set()).discard(self.transport)
        self.transport.set_protocol(proto)
        proto.connection_made(self.transport)
        if raw:
            proto.data_received(raw)

    def _upgrade_frames(self) -> None:
        """Swap this connection onto the frame-protocol terminator
        (server/frameserver.py) — the binary sibling wire — keeping
        the real transport and peer address like the aiohttp upgrade."""
        from .frameserver import FrameServerProtocol
        proto = FrameServerProtocol(self.vs)
        raw = bytes(self.buf[len(_FRAME_MAGIC):])
        self.buf.clear()
        self._closed = True          # this protocol is done
        getattr(self.vs, "_fast_conns", set()).discard(self.transport)
        self.transport.set_protocol(proto)
        proto.connection_made(self.transport)
        if raw:
            proto.data_received(raw)

    def _upgrade_replay(self, method: bytes, fid_s: str,
                        headers: dict, q: bytes = b"",
                        body: bytes = b"") -> None:
        """Upgrade when the fast path discovered mid-request that the
        full handler must serve it: reconstruct the consumed request at
        the FRONT of the buffer, then upgrade."""
        hdr_blob = b"".join(
            k.title().encode() + b": " + v.encode("latin-1") + b"\r\n"
            for k, v in headers.items())
        req = (method + b" /" + fid_s.encode() + q + b" HTTP/1.1\r\n"
               + hdr_blob + b"\r\n" + body)
        self.buf[:0] = req
        self._upgrade()


class FastAssignProtocol(asyncio.Protocol):
    """Master-side fast path for `GET /dir/assign` — the other half of
    every data-plane write (the reference answers it from an in-memory
    VolumeLayout pick + sequencer bump, master_server_handlers.go:60-99;
    that is exactly what runs here, with no HTTP framework between the
    socket and the pick). Leader-less, growth-needing, guarded-rejected
    and every non-assign request upgrade to the aiohttp app unchanged.

    The whole decision is synchronous, so a cold request is detected
    BEFORE any state changes and the original bytes simply stay in the
    buffer for aiohttp — no replay reconstruction needed."""

    _RE = re.compile(rb"^GET /dir/assign((?:\?[^ ]*)?) HTTP/1\.1$")

    __slots__ = ("ms", "buf", "transport", "peer_ip", "_closed")

    def __init__(self, ms) -> None:
        self.ms = ms
        self.buf = bytearray()
        self.transport = None
        self.peer_ip: str | None = None
        self._closed = False

    def connection_made(self, transport) -> None:
        self.transport = transport
        if not hasattr(self.ms, "_fast_conns"):
            self.ms._fast_conns = set()
        self.ms._fast_conns.add(transport)
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if peer else None

    def connection_lost(self, exc) -> None:
        self._closed = True
        getattr(self.ms, "_fast_conns", set()).discard(self.transport)

    def data_received(self, data: bytes) -> None:
        self.buf += data
        while not self._closed:
            if self.buf[:1] == _FRAME_MAGIC[:1]:
                # binary frame preamble (util/frame.py): raft RPCs,
                # volume heartbeats and client lookups ride the frame
                # fabric onto this same public port — swap protocols
                # in place once the magic is complete, exactly like
                # the volume side's raw listener
                if self.buf.startswith(_FRAME_MAGIC):
                    self._upgrade_frames()
                    return
                if len(self.buf) < len(_FRAME_MAGIC) and \
                        _FRAME_MAGIC.startswith(bytes(self.buf)):
                    return            # preamble still arriving
                self._upgrade()       # same first byte, not the magic
                return
            head_end = self.buf.find(b"\r\n\r\n")
            if head_end < 0:
                if len(self.buf) > 32 * 1024:
                    self._upgrade()
                return
            m = self._RE.match(bytes(self.buf[:self.buf.find(b"\r\n")]))
            if m is None:
                self._upgrade()
                return
            out = self._assign(m.group(1))
            if out is None:
                self._upgrade()     # cold: bytes stay buffered
                return
            del self.buf[:head_end + 4]
            self.transport.write(out)

    def _assign(self, q: bytes) -> bytes | None:
        """Synchronous assign; None => let aiohttp handle it."""
        ms = self.ms
        if _fp_pending("master.assign"):
            return None             # armed failpoint: full handler fires it
        if not ms.is_leader:
            return None             # leader proxy path
        count_s = collection = replication = ttl = b""
        if q not in (b"", b"?"):
            for kv in q[1:].split(b"&"):
                k, _, val = kv.partition(b"=")
                if k == b"count":
                    count_s = val
                elif k == b"collection":
                    collection = val
                elif k == b"replication":
                    replication = val
                elif k == b"ttl":
                    ttl = val
                elif k not in (b"dataCenter", b""):
                    return None     # unknown knob: full handler decides
                elif k == b"dataCenter" and val:
                    return None     # dc-constrained growth: cold
        if b"%" in q or b"+" in q:
            return None             # urlencoded values: full parser
        if not ms.guard.empty and not ms.guard.allows(self.peer_ip):
            return _R401_IP
        try:
            count = int(count_s or 1)
        except ValueError:
            return None
        coll = collection.decode()
        repl = replication.decode() or ms.default_replication
        ttl_s = ttl.decode()
        try:
            from ..storage.super_block import ReplicaPlacement
            rp = ReplicaPlacement.parse(repl)
        except ValueError as e:
            return _json_err(400, "Bad Request", str(e))
        from ..storage import types as t
        lay = ms._layout(coll, repl, ttl_s)
        vid = lay.pick_for_write(ms.topo, rp.copy_count)
        if vid is None:
            return None             # growth: serialized in aiohttp
        try:
            key = ms.seq.next_file_id(count)
        except SequenceBehind:
            # committed fid window spent: the full handler raft-commits
            # a fresh reservation before answering (multi-master)
            return None
        ms.count_assign()
        fid = str(t.FileId(vid, key, t.random_cookie()))
        node = ms.topo.lookup(vid)[0]
        out = {"fid": fid, "url": node.url, "publicUrl": node.public_url,
               "count": count}
        if ms.jwt_key:
            from ..security.jwt import gen_jwt
            out["auth"] = gen_jwt(ms.jwt_key, fid)
        body = json.dumps(out).encode()
        return (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json; charset=utf-8\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    def _upgrade(self) -> None:
        proto = self.ms._runner.server()
        raw = bytes(self.buf)
        self.buf.clear()
        self._closed = True
        getattr(self.ms, "_fast_conns", set()).discard(self.transport)
        self.transport.set_protocol(proto)
        proto.connection_made(self.transport)
        if raw:
            proto.data_received(raw)

    def _upgrade_frames(self) -> None:
        """Swap onto the master's frame terminator
        (master/frameadapter.py). The assign ACCELERATOR worker has no
        frame surface (its `ms` is not a MasterServer) — there a frame
        preamble upgrades onto the proxy app, which closes the
        connection and the client's channel falls back to HTTP."""
        factory = getattr(self.ms, "frame_protocol", None)
        if factory is None:
            self._upgrade()
            return
        proto = factory()
        raw = bytes(self.buf[len(_FRAME_MAGIC):])
        self.buf.clear()
        self._closed = True
        getattr(self.ms, "_fast_conns", set()).discard(self.transport)
        self.transport.set_protocol(proto)
        proto.connection_made(self.transport)
        if raw:
            proto.data_received(raw)


class AcceleratorAssignProtocol(FastAssignProtocol):
    """Raw listener of a master assign-accelerator worker
    (server/workers.py AssignAccelerator): identical wire discipline to
    FastAssignProtocol (the `ms` slot holds the accelerator, which
    exposes the same `_runner`/`_fast_conns` surface), but the assign
    decision comes from the accelerator's leased ids + writable-set
    snapshot instead of the live topology, and a cold request upgrades
    onto the accelerator's transparent proxy app."""

    __slots__ = ()

    def _assign(self, q: bytes) -> bytes | None:
        return self.ms.fast_assign(q, self.peer_ip)
