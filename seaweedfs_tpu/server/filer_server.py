"""Filer server: POSIX-ish HTTP namespace over the volume tier.

Reference: weed/server/filer_server_handlers_read.go:21-260 (streaming +
range reads over chunks), _write.go + _write_autochunk.go (auto-chunked
uploads, default 256MB chunks), filer_grpc_server.go (metadata API incl.
AtomicRenameEntry). The gRPC surface maps to JSON endpoints under /__api__/.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp
from aiohttp import web

from ..filer.entry import Attr, Entry
from ..filer.filechunks import (FileChunk, etag as chunks_etag, total_size)
from ..filer.filer import Filer, FilerError
from ..filer.stream import stream_chunk_views
from ..storage import types as t
from ..util.client import OperationError, WeedClient
from ..util.httprange import RangeError, parse_range
from ..util.singleflight import SingleFlight
from ..security import tls


class FilerServer:
    def __init__(self, filer: Filer, master_url: str,
                 ip: str = "127.0.0.1", port: int = 8888,
                 chunk_size: int = 32 * 1024 * 1024,
                 collection: str = "", replication: str = "",
                 data_center: str = "",
                 redirect_on_read: bool = False,
                 disable_dir_listing: bool = False,
                 dir_list_limit: int = 100_000,
                 cache_mem_bytes: int = 0,
                 cache_dir: str = "",
                 shard_id: int = 0, shard_of: int = 1,
                 shard_peers: dict | None = None,
                 shard_split_mbps: float = 8.0):
        # -cache.mem/-cache.dir: tiered whole-chunk read cache riding
        # the WeedClient (util/chunk_cache); 0 disables
        self.cache_mem_bytes = cache_mem_bytes
        self.cache_dir = cache_dir
        self.filer = filer
        self.master_url = master_url
        self.ip = ip
        self.port = port
        self.chunk_size = chunk_size
        self.collection = collection
        self.replication = replication
        # command/filer.go:50-54 knobs
        self.data_center = data_center
        self.redirect_on_read = redirect_on_read
        self.disable_dir_listing = disable_dir_listing
        self.dir_list_limit = dir_list_limit
        self._runner: web.AppRunner | None = None
        self._tasks: list[asyncio.Task] = []
        self.client: WeedClient | None = None
        # -shard.id/-shard.of: this process owns a prefix range of the
        # namespace per the raft-committed shard map (filer/shard.py)
        self.shard = None
        if shard_of > 1:
            from ..filer.shard import ShardNode
            self.shard = ShardNode(self, shard_id, shard_of,
                                   peers=shard_peers,
                                   split_mbps=shard_split_mbps)
        # hot-listing collapse: identical concurrent list ops on one
        # directory share a single store query, fenced by a per-dir
        # generation the write listener bumps (util/singleflight.py)
        self._list_sf = SingleFlight()
        self._dir_gens: dict[str, int] = {}
        self._fence_epoch = 0
        self.filer.listeners.append(self._on_entry_change)
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        from ..stats import metrics
        from ..util import tracing

        @web.middleware
        async def timing(request, handler):
            from .. import qos
            t0 = time.perf_counter()
            kind = "read" if request.method in ("GET", "HEAD") \
                else "write"
            reserved = request.path.startswith("/__")
            # tenant admission (seaweedfs_tpu/qos/): classified on the
            # AWS credential / JWT identity when present, the default
            # class otherwise; shed answers cost no chunk work
            ctrl = None if reserved else qos.admission()
            dec = None
            if ctrl is not None:
                # weedlint: ignore[lock-acquire] admission decision, not a mutex: a denied Decision holds nothing, and the admitted path releases in the finally below
                dec = await ctrl.acquire(
                    "filer", kind,
                    qos.tenant_from_headers(request.headers))
                if not dec.admitted:
                    return web.json_response(
                        {"error": "request shed", "reason": dec.reason},
                        status=dec.status,
                        headers={"Retry-After": str(
                            max(1, int(dec.retry_after_s + 0.999)))})
                qos.set_current_class(dec.cls)
            # filer-tier entry span; the reserved introspection paths
            # (/__metrics__, /__debug__/...) stay out of the ring
            sp = (tracing._NOOP if reserved
                  else tracing.start_root(
                      "filer", kind, headers=request.headers,
                      **({"tenant": dec.tenant} if dec is not None
                         else {})))
            try:
                with sp:
                    try:
                        resp = await handler(request)
                    except web.HTTPException as e:
                        sp.status = str(e.status)
                        raise
                    sp.status = ("ok" if resp.status < 400
                                 else str(resp.status))
                    return resp
            finally:
                dt = time.perf_counter() - t0
                if dec is not None:
                    ctrl.release(dec)
                    ctrl.observe("filer", kind, dec, dt)
                if metrics.HAVE_PROMETHEUS:
                    metrics.FILER_REQUEST_TIME.labels(kind).observe(dt)

        app = web.Application(client_max_size=4 * 1024 * 1024 * 1024,
                              middlewares=[timing])
        api = [
            ("POST", "/__api__/rename", self.h_api_rename),
            ("GET", "/__api__/lookup", self.h_api_lookup),
            ("GET", "/__api__/list", self.h_api_list),
            ("POST", "/__api__/entry", self.h_api_create_entry),
            ("POST", "/__api__/assign", self.h_api_assign),
            ("POST", "/__api__/delete", self.h_api_delete),
            ("POST", "/__api__/shard/ingest", self.h_shard_ingest),
        ]
        for method, path, handler in api:
            app.router.add_route(method, path, handler)
        from ..util import failpoints
        app.router.add_route("*", "/__debug__/failpoints",
                             failpoints.handle_debug)
        # reserved-prefix twins of the volume server's /debug/traces//
        # debug/requests (a stored file named /debug/traces must stay
        # reachable); one shared implementation across filer/S3/WebDAV
        h_traces, h_requests = tracing.debug_handlers()
        app.router.add_get("/__debug__/traces", h_traces)
        app.router.add_get("/__debug__/requests", h_requests)
        # flight-recorder twins (stats/timeline.py): timeline, event
        # journal, SLO health — same shared trio as master/S3/WebDAV
        from ..stats.timeline import recorder_handlers
        h_tl, h_ev, h_hl = recorder_handlers()
        app.router.add_get("/__debug__/timeline", h_tl)
        app.router.add_post("/__debug__/timeline", h_tl)
        app.router.add_get("/__debug__/events", h_ev)
        app.router.add_get("/__debug__/health", h_hl)
        from .. import qos
        app.router.add_get("/__debug__/qos", qos.debug_handler)
        app.router.add_get("/__debug__/shards", self.h_debug_shards)
        from ..stats import profiler
        from ..util import pprof
        app.router.add_get("/__debug__/profile", profiler.debug_handler())
        app.router.add_get("/__debug__/pprof", pprof.debug_handler())
        # reserved-prefix path (like /__api__, /__debug__) so a stored
        # file named /metrics is never shadowed; exposes the chunk-cache
        # hit/miss/byte counters among the rest of the registry
        app.router.add_get("/__metrics__", self.h_metrics)
        app.router.add_route("GET", "/{path:.*}", self.h_get)
        app.router.add_route("HEAD", "/{path:.*}", self.h_get)
        app.router.add_route("POST", "/{path:.*}", self.h_post)
        app.router.add_route("PUT", "/{path:.*}", self.h_post)
        app.router.add_route("DELETE", "/{path:.*}", self.h_delete)
        return app

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    async def h_metrics(self, req: web.Request) -> web.Response:
        from ..stats.metrics import metrics_text
        return web.Response(body=metrics_text(),
                            content_type="text/plain")


    async def start(self) -> None:
        cc = None
        if self.cache_mem_bytes > 0:
            from ..util import tracing
            from ..util.chunk_cache import TieredChunkCache
            # ctor makedirs the disk tier — off the loop: under
            # `weed-tpu server` this loop already serves other daemons
            cc = await tracing.run_in_executor(
                lambda: TieredChunkCache(self.cache_mem_bytes,
                                         disk_dir=self.cache_dir or None))
        self.client = WeedClient(self.master_url, chunk_cache=cc)
        await self.client.__aenter__()
        # watch-fed location map: hot-path reads never lookup the master
        # (reference filer embeds wdclient the same way)
        from ..util.masterclient import MasterClient
        self.master_client = MasterClient(self.master_url, name="filer")
        await self.master_client.start()
        self.client.attach_master_client(self.master_client)
        self.filer.chunk_deleter = self._queue_chunk_deletes
        self._pending: list[str] = []
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.ip, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        self._tasks.append(asyncio.create_task(self._chunk_gc_loop()))
        if self.shard is not None:
            await self.shard.start()

    async def stop(self) -> None:
        if self.shard is not None:
            await self.shard.stop()
        for t in self._tasks:
            t.cancel()
        mc = getattr(self, "master_client", None)
        if mc is not None:
            await mc.stop()
        if self.client:
            await self.client.__aexit__()
        if self._runner:
            await self._runner.cleanup()
        self.filer.close()

    # ---- async chunk GC (filer_deletion.go) ----

    def _queue_chunk_deletes(self, fids: list[str]) -> None:
        self._pending.extend(fids)

    async def _chunk_gc_loop(self) -> None:
        from ..util import glog
        from ..util.client import OperationError
        while True:
            await asyncio.sleep(1.0)
            batch, self._pending = self._pending[:1024], self._pending[1024:]
            if batch:
                try:
                    await self.client.delete_fids(batch)
                except (OperationError, aiohttp.ClientError,
                        asyncio.TimeoutError, OSError) as e:
                    # transient tier outage: requeue, but visibly — a
                    # permanently failing GC loop leaks chunks forever
                    glog.warning("filer chunk gc: %d fids requeued: %s",
                                 len(batch), e)
                    self._pending.extend(batch)

    # ---- normalize ----

    @staticmethod
    def _path(req: web.Request) -> str:
        p = "/" + req.match_info["path"]
        while "//" in p:
            p = p.replace("//", "/")
        return p if p == "/" else p.rstrip("/")

    # ---- shard ownership (filer/shard.py) ----

    async def _shard_gate(self, req: web.Request,
                          path: str) -> web.Response | None:
        """Ownership enforcement: a request for a path this shard does
        not own bounces with ``307 + X-Shard-Owner/-Prefix/-Epoch`` so
        the client folds the owner into its route cache (the learned-
        leader discipline). ``local=1`` marks a peer-internal hop that
        must be answered from the local store, never re-routed."""
        if self.shard is None or req.query.get("local") == "1":
            return None
        from ..util import failpoints
        # chaos site: the per-request routing decision
        await failpoints.fail("filer.shard.route")
        if self.shard.is_local(path):
            self.shard.counters["local"] += 1
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.FILER_SHARD_REQUESTS.labels("local").inc()
            return None
        hdrs = self.shard.redirect_headers(path)
        if hdrs is None:
            # owner unknown (map still syncing): 503 so the client
            # retries — a routed miss must never read as a 404
            return web.json_response(
                {"error": "shard owner unknown", "path": path},
                status=503, headers={"Retry-After": "1"})
        loc = tls.url(hdrs["X-Shard-Owner"], req.path_qs)
        return web.json_response(
            {"error": "wrong shard", "owner": hdrs["X-Shard-Owner"]},
            status=307, headers=dict(hdrs, Location=loc))

    def _on_entry_change(self, old_entry, new_entry) -> None:
        """Write listener: bump the listing generation of every parent
        directory a mutation touches (the singleflight fill-token
        fence — an in-flight collapsed fill keyed on the old
        generation can no longer satisfy new readers)."""
        for e in (old_entry, new_entry):
            if e is not None:
                self.bump_gen_fence(e.dir_path)

    def bump_gen_fence(self, dir_path: str, subtree: bool = False) -> None:
        d = dir_path or "/"
        self._dir_gens[d] = self._dir_gens.get(d, 0) + 1
        if subtree or len(self._dir_gens) > 8192:
            # wholesale invalidation: subtree tombstone/migration, or
            # the per-dir table growing without bound
            self._dir_gens.clear()
            self._fence_epoch += 1

    async def _list_entries(self, path: str, start_file: str,
                            inclusive: bool, limit: int) -> list[Entry]:
        """One directory page: singleflight-collapsed, store query off
        the event loop, merged across shards owning rules below the
        directory when sharded."""
        gen = self._dir_gens.get(path, 0)
        key = (f"{path}|{start_file}|{int(inclusive)}|{limit}"
               f"|{gen}|{self._fence_epoch}")

        async def fill() -> list[Entry]:
            if self.shard is not None:
                self.shard.counters["local"] += 1
                return await self.shard.merged_list(
                    path, start_file, inclusive, limit)
            from ..util import tracing
            return await tracing.run_in_executor(
                lambda: self.filer.list_directory_entries(
                    path, start_file, inclusive, limit))

        return await self._list_sf.do(key, fill)

    async def _shard_fallback_entry(self, path: str) -> Entry | None:
        """Local miss during a split's cleanup window: double-read the
        old owner (it holds entries not yet streamed over) so the
        migration window never surfaces a 404."""
        if self.shard is None:
            return None
        src = self.shard.double_read_source(path)
        if not src:
            return None
        d = await self.shard.forward_lookup(src, path)
        if d is None:
            return None
        from ..filer.shard import _entry_from_json
        return _entry_from_json(d)

    async def h_shard_ingest(self, req: web.Request) -> web.Response:
        """Migration sink for split/move batches (idempotent,
        mtime-gated; see ShardNode.ingest)."""
        if self.shard is None:
            return web.json_response(
                {"error": "not a sharded filer"}, status=400)
        body = await req.json()
        n = await self.shard.ingest(body.get("entries", []))
        if int(body.get("epoch") or 0) > self.shard.map.epoch:
            await self.shard.adopt_epoch(int(body["epoch"]))
        # a migrated batch changes listings wholesale under the moved
        # prefix: drop every collapsed fill
        self.bump_gen_fence("/", subtree=True)
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FILER_SHARD_REQUESTS.labels("ingest").inc()
        return web.json_response({"ingested": n})

    async def h_debug_shards(self, req: web.Request) -> web.Response:
        if self.shard is not None:
            st = self.shard.status()
        else:
            count = getattr(self.filer.store, "count_entries", None)
            st = {"shard": 0, "of": 1, "url": self.url, "epoch": 0,
                  "entries": count() if count is not None else -1,
                  "rules": [["/", 0]], "owners": {},
                  "moves": [], "counters": {}}
        st["singleflight"] = {"calls": self._list_sf.calls,
                              "collapsed": self._list_sf.collapsed}
        return web.json_response(st)

    # ---- read path ----

    async def h_get(self, req: web.Request) -> web.StreamResponse:
        path = self._path(req)
        bounce = await self._shard_gate(req, path)
        if bounce is not None:
            return bounce
        entry = self.filer.find_entry(path)
        if entry is None:
            entry = await self._shard_fallback_entry(path)
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        if entry.is_directory:
            return await self._list_dir(req, path)
        size = entry.size
        status = 200
        offset, length = 0, size
        try:
            rng = parse_range(req.headers.get("Range", ""), size)
        except RangeError:
            return web.Response(
                status=416, headers={"Content-Range": f"bytes */{size}"})
        if rng is not None:
            offset, length = rng
            status = 206
        headers = {
            "Accept-Ranges": "bytes",
            "Content-Length": str(length),
            "Etag": f'"{chunks_etag(entry.chunks)}"',
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT",
                time.gmtime(entry.attr.mtime or 0)),
        }
        if status == 206:
            headers["Content-Range"] = f"bytes {offset}-{offset+length-1}/{size}"
        ct = entry.attr.mime or "application/octet-stream"
        if req.method == "HEAD":
            return web.Response(status=status, headers=headers,
                                content_type=ct)
        if self.redirect_on_read and rng is None \
                and len(entry.chunks) == 1 \
                and entry.chunks[0].offset == 0 \
                and entry.chunks[0].size == size:
            # -redirectOnRead (filer.go:50, handleSingleChunk): bounce
            # the client straight to the volume server instead of
            # proxying. Only when the single chunk IS the whole file —
            # a sparse entry's raw blob would be the wrong bytes.
            url = None
            try:
                url = await self.client.lookup_file_id(
                    entry.chunks[0].file_id)
            except (OperationError, IndexError, aiohttp.ClientError,
                    asyncio.TimeoutError):
                pass  # fall through to the proxy path
            if url:
                raise web.HTTPFound(location=url)
        resp = web.StreamResponse(status=status, headers=headers)
        resp.content_type = ct
        await resp.prepare(req)
        # stream chunk views (filer2/stream.go StreamContent) under a
        # stream span: its SELF time is the filer's chunk fan-out +
        # assembly cost, its client children are the volume-tier hops
        from ..util import tracing
        with tracing.start("filer", "stream",
                           chunks=len(entry.chunks)) as sp:
            try:
                sent = 0
                async for data in stream_chunk_views(
                        self.client, entry.chunks, offset, length):
                    await resp.write(data)
                    sent += len(data)
                sp.nbytes = sent
            except OperationError:
                # headers already sent: abort the connection so the
                # client sees a transport error, not a silently short
                # body
                sp.status = "error"
                if req.transport is not None:
                    req.transport.close()
                return resp
        await resp.write_eof()
        return resp

    async def _list_dir(self, req: web.Request, path: str) -> web.Response:
        if self.disable_dir_listing:
            # -disableDirListing (filer.go:51)
            return web.json_response(
                {"error": "directory listing is disabled"}, status=405)
        limit = int(req.query.get("limit", 1000))
        if limit <= 0:
            # SQLite treats LIMIT -1 as unlimited — a negative client
            # value must not bypass the cap
            limit = 1000
        limit = min(limit, self.dir_list_limit)
        last = req.query.get("lastFileName", "")
        entries = await self._list_entries(path, last, False, limit)
        return web.json_response({
            "Path": path,
            "Entries": [self._entry_json(e) for e in entries],
            "ShouldDisplayLoadMore": len(entries) == limit,
        })

    @staticmethod
    def _entry_json(e: Entry) -> dict:
        return {
            "FullPath": e.full_path,
            "Mtime": e.attr.mtime, "Crtime": e.attr.crtime,
            "Mode": e.attr.mode, "Uid": e.attr.uid, "Gid": e.attr.gid,
            "Mime": e.attr.mime, "Replication": e.attr.replication,
            "Collection": e.attr.collection, "TtlSec": e.attr.ttl_sec,
            "IsDirectory": e.is_directory, "FileSize": e.size,
            "chunks": [c.to_dict() for c in e.chunks],
            "extended": e.extended,
        }

    # ---- write path (auto-chunking, _write_autochunk.go:23-188) ----

    async def h_post(self, req: web.Request) -> web.Response:
        path = self._path(req)
        if "mv.from" in req.query:
            return await self._rename(req, req.query["mv.from"], path)
        bounce = await self._shard_gate(req, path)
        if bounce is not None:
            return bounce
        raw_path = req.match_info["path"]
        if (raw_path.endswith("/") and raw_path != "") \
                or req.query.get("mkdir") == "true":
            from ..filer.entry import new_directory_entry
            self.filer.create_entry(new_directory_entry(path))
            return web.json_response({"name": path}, status=201)

        mime = ""
        reader = None
        ctype = req.headers.get("Content-Type", "")
        filename = ""
        if ctype.startswith("multipart/form-data"):
            mp = await req.multipart()
            async for part in mp:
                if part.filename or part.name in ("file", None):
                    filename = part.filename or ""
                    pct = part.headers.get("Content-Type", "")
                    if pct and pct != "application/octet-stream":
                        mime = pct
                    reader = part
                    break
            if reader is None:
                return web.json_response({"error": "no file part"},
                                         status=400)
        else:
            reader = req.content
            if ctype and ctype != "application/octet-stream":
                mime = ctype.split(";")[0]

        collection = req.query.get("collection", self.collection)
        replication = req.query.get("replication", self.replication)
        ttl = req.query.get("ttl", "")
        try:
            # validate BEFORE uploading any chunk: a bad ttl must be an
            # early 400, not a post-upload 500 with chunk rollback (or a
            # silent drop on a zero-byte file)
            ttl_sec = t.TTL.parse(ttl).minutes * 60
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        chunks: list[FileChunk] = []
        offset = 0
        # filer-tier write span: the chunk fan-out + entry commit,
        # with the per-chunk volume uploads as client children
        from ..util import tracing
        with tracing.start("filer", "write") as fsp:
            try:
                while True:
                    data = await _read_up_to(reader, self.chunk_size)
                    if not data:
                        break
                    a = await self.client.assign(
                        collection=collection, replication=replication,
                        ttl=ttl, data_center=self.data_center)
                    up = await self.client.upload(
                        a["fid"], a["url"], data, mime=mime, ttl=ttl,
                        auth=a.get("auth", ""))
                    chunks.append(FileChunk(
                        file_id=a["fid"], offset=offset,
                        size=len(data), mtime=time.time_ns(),
                        etag=up.get("eTag", "")))
                    offset += len(data)
                    if len(data) < self.chunk_size:
                        break
            except OperationError as e:
                # roll back uploaded chunks
                self.filer.delete_chunks([c.file_id for c in chunks])
                fsp.status = "error"
                return web.json_response({"error": str(e)}, status=500)

            now = time.time()
            entry = Entry(
                full_path=path,
                attr=Attr(mtime=now, crtime=now, mode=0o660, mime=mime,
                          replication=replication,
                          collection=collection, ttl_sec=ttl_sec),
                chunks=chunks)
            try:
                self.filer.create_entry(entry)
            except FilerError as e:
                self.filer.delete_chunks([c.file_id for c in chunks])
                fsp.status = "error"
                return web.json_response({"error": str(e)}, status=400)
            fsp.set("chunks", len(chunks))
            fsp.nbytes = offset
        return web.json_response(
            {"name": filename or entry.name, "size": offset}, status=201)

    async def _rename(self, req: web.Request, src: str,
                      dst: str) -> web.Response:
        """Rename, shard-aware: the SOURCE shard drives. Same-shard
        renames stay the plain atomic store move; a cross-shard rename
        runs as a raft-journaled two-phase move (intent committed,
        copy-then-tombstone, idempotent replay on crash)."""
        if self.shard is not None and req.query.get("local") != "1":
            from ..util import failpoints
            # chaos site: the rename routing decision
            await failpoints.fail("filer.shard.route")
            if not self.shard.is_local(src):
                hdrs = self.shard.redirect_headers(src)
                if hdrs is None:
                    return web.json_response(
                        {"error": "shard owner unknown", "path": src},
                        status=503, headers={"Retry-After": "1"})
                loc = tls.url(hdrs["X-Shard-Owner"], req.path_qs)
                return web.json_response(
                    {"error": "wrong shard",
                     "owner": hdrs["X-Shard-Owner"]},
                    status=307, headers=dict(hdrs, Location=loc))
            if not self.shard.is_local(dst):
                try:
                    await self.shard.cross_shard_rename(src, dst)
                except (OSError, ValueError) as e:
                    return web.json_response({"error": str(e)},
                                             status=409)
                return web.json_response({"ok": True, "moved": True})
        try:
            self.filer.rename_entry(src, dst)
        except FilerError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"ok": True})

    async def h_delete(self, req: web.Request) -> web.Response:
        path = self._path(req)
        bounce = await self._shard_gate(req, path)
        if bounce is not None:
            return bounce
        recursive = req.query.get("recursive") == "true"
        try:
            self.filer.delete_entry(path, recursive=recursive,
                                    ignore_recursive_error=req.query.get(
                                        "ignoreRecursiveError") == "true")
        except FilerError as e:
            code = 404 if "not found" in str(e) else 400
            return web.json_response({"error": str(e)}, status=code)
        return web.Response(status=204)

    # ---- metadata API (filer.proto analog) ----

    async def h_api_lookup(self, req: web.Request) -> web.Response:
        path = req.query["path"]
        bounce = await self._shard_gate(req, path)
        if bounce is not None:
            return bounce
        entry = self.filer.find_entry(path)
        if entry is None:
            entry = await self._shard_fallback_entry(path)
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(self._entry_json(entry))

    async def h_api_list(self, req: web.Request) -> web.Response:
        path = req.query["path"]
        bounce = await self._shard_gate(req, path)
        if bounce is not None:
            return bounce
        limit = int(req.query.get("limit", 1024))
        if limit <= 0:
            # same clamp as _list_dir: SQLite reads LIMIT -1 as
            # unlimited, so a negative value must not bypass the cap
            limit = 1000
        limit = min(limit, self.dir_list_limit)
        if req.query.get("local") == "1":
            # peer-internal hop of a merged listing: local page only
            entries = self.filer.list_directory_entries(
                path, req.query.get("startFile", ""),
                req.query.get("inclusive") == "true", limit)
        else:
            entries = await self._list_entries(
                path, req.query.get("startFile", ""),
                req.query.get("inclusive") == "true", limit)
        return web.json_response(
            {"entries": [self._entry_json(e) for e in entries]})

    async def h_api_create_entry(self, req: web.Request) -> web.Response:
        body = await req.json()
        bounce = await self._shard_gate(req, body.get("FullPath", "/"))
        if bounce is not None:
            return bounce
        e = Entry(
            full_path=body["FullPath"],
            attr=Attr(mtime=body.get("Mtime", time.time()),
                      crtime=body.get("Crtime", time.time()),
                      mode=body.get("Mode", 0o660),
                      uid=body.get("Uid", 0), gid=body.get("Gid", 0),
                      mime=body.get("Mime", ""),
                      replication=body.get("Replication", ""),
                      collection=body.get("Collection", ""),
                      ttl_sec=body.get("TtlSec", 0)),
            chunks=[FileChunk.from_dict(c) for c in body.get("chunks", [])],
            extended=body.get("extended", {}))
        try:
            self.filer.create_entry(e)
        except FilerError as err:
            return web.json_response({"error": str(err)}, status=400)
        return web.json_response({"ok": True})

    async def h_api_rename(self, req: web.Request) -> web.Response:
        return await self._rename(req, req.query["from"],
                                  req.query["to"])

    async def h_api_assign(self, req: web.Request) -> web.Response:
        try:
            a = await self.client.assign(
                collection=req.query.get("collection", self.collection),
                replication=req.query.get("replication", self.replication),
                ttl=req.query.get("ttl", ""),
                data_center=self.data_center)
        except OperationError as e:
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(a)

    async def h_api_delete(self, req: web.Request) -> web.Response:
        fids = (await req.json()).get("fids", [])
        self.filer.delete_chunks(fids)
        return web.json_response({"ok": True})


async def _read_up_to(reader, n: int) -> bytes:
    """Read exactly n bytes unless EOF; handles both aiohttp StreamReader
    (short reads possible) and multipart BodyPartReader."""
    out = bytearray()
    while len(out) < n:
        if hasattr(reader, "read_chunk"):
            chunk = await reader.read_chunk(min(64 * 1024, n - len(out)))
        else:
            chunk = await reader.read(n - len(out))
        if not chunk:
            break
        out.extend(chunk)
    return bytes(out)
