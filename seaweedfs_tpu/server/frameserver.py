"""Frame-protocol listener adapter for the volume server.

A connection that opens with the frame MAGIC (util/frame.py) — on the
public/private TCP port (sniffed by the raw HTTP fast path) or on the
per-worker unix socket — lands here. This is the THIRD transport
adapter over the unified wire layer (server/wire.py), beside the raw
HTTP listener and the aiohttp app: it builds the same
:class:`WireRequest`, calls the same serve_read/serve_write/
serve_delete/serve_batch, and renders the :class:`WireResponse` as a
RESP frame — so the needle cache, tracing, failpoints, Range/
conditional semantics, replication fan-out and group commit stay
wired exactly once. Cold bodies still go disk->socket: a sendfile
response writes the frame header, then ``loop.sendfile``s the needle
region into the SAME frame's payload slot.

Request routing over frames:

* ``/<vid>,<fid>`` GET/HEAD/POST/PUT/DELETE — the needle API;
* ``/batch`` — the pipelined multi-needle GET;
* ``/admin/ec/shard_read`` — the batched EC shard gather.

Anything the frame transport cannot express (chunked-manifest
assembly, jwt-guarded writes on an identity-less connection,
multipart) answers with ``FLAG_FALLBACK`` and the caller retries over
HTTP — the exact degradation a peer that predates the protocol
produces. Replica fan-out writes (x-raw-needle) and replicate-typed
deletes are served over frames under the same -whiteList policy the
HTTP listeners apply; on jwt-secured clusters the HELLO handshake
itself refuses connections without a verified identity claim.

Under ``-workers``, a frame request for a sibling-owned vid arriving
WITHOUT the launch token is forwarded over the server's own sibling
frame channel (the frame twin of the aiohttp worker-routing
middleware), so an external pipelining client never pays an HTTP
downgrade just because SO_REUSEPORT handed it the wrong worker.
"""

from __future__ import annotations

import asyncio

from ..storage import types as t
from ..util import failpoints, glog, tracing
from ..util.frame import (FLAG_FALLBACK, FrameChannelError, FrameDecoder,
                          FrameError, GOAWAY, HELLO, HELLO_OK, MAGIC,
                          MAX_FRAME, REQ, RESP, encode_frame)
from . import wire

_OPS = {"GET": "read", "HEAD": "read", "POST": "write", "PUT": "write",
        "DELETE": "delete"}


def _count_frames(side: str, hop: str, n: int = 1) -> None:
    from ..stats import metrics
    if metrics.HAVE_PROMETHEUS:
        metrics.FRAME_REQUESTS.labels(side, hop).inc(n)


class FrameServerProtocol(asyncio.Protocol):
    """Per-connection frame terminator (server side)."""

    __slots__ = ("vs", "transport", "peer_ip", "dec", "hop", "authed",
                 "_hello", "_closed", "_tasks", "_write_lock", "_pre")

    def __init__(self, vs) -> None:
        self.vs = vs
        self.transport = None
        self.peer_ip: str | None = None
        self.dec = FrameDecoder()
        self.hop = False              # token-authenticated worker hop
        # cluster identity: worker token OR a verified jwt HELLO claim
        # (frames from a peer holding the cluster signing key)
        self.authed = False
        self._hello = False
        self._closed = False
        self._tasks: set = set()
        # clients always open with the MAGIC preamble; the raw TCP
        # listener strips it while sniffing, but connections landing
        # here directly (the unix socket) still carry it — buffer just
        # enough to strip an optional leading MAGIC
        self._pre: bytearray | None = bytearray()
        # responses interleave across request tasks, but each frame's
        # bytes (and a sendfile region inside one) must hit the
        # transport contiguously
        self._write_lock = asyncio.Lock()

    # -- asyncio.Protocol --

    def connection_made(self, transport) -> None:
        self.transport = transport
        if not hasattr(self.vs, "_fast_conns"):
            self.vs._fast_conns = set()
        self.vs._fast_conns.add(transport)
        peer = transport.get_extra_info("peername")
        self.peer_ip = peer[0] if isinstance(peer, tuple) and peer \
            else None
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                import socket as _s
                if sock.family == getattr(_s, "AF_INET", None) or \
                        sock.family == getattr(_s, "AF_INET6", None):
                    sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
            except OSError:
                pass

    def connection_lost(self, exc) -> None:
        self._closed = True
        getattr(self.vs, "_fast_conns", set()).discard(self.transport)
        for task in self._tasks:
            task.cancel()

    def data_received(self, data: bytes) -> None:
        if self._pre is not None:
            self._pre += data
            if self._pre[:1] == MAGIC[:1] and \
                    len(self._pre) < len(MAGIC) and \
                    MAGIC.startswith(bytes(self._pre)):
                return                # preamble still arriving
            data = bytes(self._pre)
            self._pre = None
            if data.startswith(MAGIC):
                data = data[len(MAGIC):]
            # anything else goes to the decoder as-is: a real frame
            # starts with a small big-endian length, garbage draws a
            # FrameError -> GOAWAY below
            if not data:
                return
        try:
            frames = self.dec.feed(data)
        except FrameError as e:
            glog.V(1).infof("frame conn from %s: %s", self.peer_ip, e)
            self._goaway(str(e))
            return
        for fr in frames:
            self._handle(fr)

    # -- frame dispatch --

    def _goaway(self, msg: str) -> None:
        if self._closed:
            return
        try:
            self.transport.write(encode_frame(GOAWAY, 0, {"error": msg}))
        except OSError:
            pass
        self._closed = True
        self.transport.close()

    def _handle(self, fr) -> None:
        if not self._hello:
            if fr.type != HELLO:
                self._goaway("expected HELLO")
                return
            wc = self.vs.worker_ctx
            token = str(fr.meta.get("token", "") or "")
            self.hop = wc is not None and wc.token_ok(token)
            self.authed = self.hop or self._verify_identity(
                str(fr.meta.get("id", "") or ""))
            if getattr(self.vs, "jwt_key", "") and not self.authed:
                # jwt-secured cluster: an unauthenticated (or wrong-
                # identity) HELLO is refused BEFORE any payload is
                # served — connection identity is part of the security
                # model, not a courtesy
                self._goaway("hello identity required "
                             "(jwt-secured cluster)")
                return
            self._hello = True
            self.transport.write(encode_frame(
                HELLO_OK, fr.req_id,
                {"v": 1, "worker": wc.index if wc else 0}))
            return
        if fr.type != REQ:
            return                    # unknown/late types ignored
        task = asyncio.get_running_loop().create_task(self._serve(fr))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _verify_identity(self, ident: str) -> bool:
        """A HELLO ``id`` claim: a jwt minted from the cluster signing
        key, bound to the fixed handshake fid (util/frame.py
        HELLO_IDENTITY_FID) so per-needle write tokens can never be
        replayed as channel identities."""
        key = getattr(self.vs, "jwt_key", "")
        if not key or not ident:
            return False
        from ..security.jwt import JwtError, decode_jwt
        from ..util.frame import HELLO_IDENTITY_FID
        try:
            return decode_jwt(key, ident).get(
                "fid") == HELLO_IDENTITY_FID
        except JwtError:
            return False

    def _hop_label(self) -> str:
        """Low-cardinality hop classification for the server-side
        counters: the launch-token worker hop and unix-socket
        connections are intra-host siblings, everything else is the
        inter-host fabric."""
        return "sibling" if (self.hop or self.peer_ip is None) \
            else "interhost"

    async def _serve(self, fr) -> None:
        _count_frames("server", self._hop_label())
        req_id = fr.req_id
        method = str(fr.meta.get("m", "GET")).upper()
        path = str(fr.meta.get("p", ""))
        query = fr.meta.get("q") or {}
        headers = {str(k).lower(): str(v)
                   for k, v in (fr.meta.get("h") or {}).items()}
        if not isinstance(query, dict):
            query = {}
        try:
            resp = await self._route(method, path, query, headers,
                                     fr.payload)
        except asyncio.CancelledError:
            raise
        except Exception as e:        # a handler bug must not wedge
            glog.warning("frame request %s %s: %s: %s", method, path,
                         type(e).__name__, e)
            resp = wire.json_err(500, f"{type(e).__name__}: {e}")
        if resp is None:
            await self._send_fallback(req_id)
            return
        await self._send_response(req_id, resp)

    async def _route(self, method: str, path: str, query: dict,
                     headers: dict, body: bytes):
        """Returns a WireResponse, or None => FLAG_FALLBACK."""
        vs = self.vs
        wc = vs.worker_ctx
        if path == "/batch":
            wr = wire.WireRequest(
                method="GET", fid_s="", query=query, headers=headers,
                peer_ip=self.peer_ip, body=body or None, raw=True,
                worker_hop=self.hop)
            with tracing.start_root("volume", "batch",
                                    headers=headers) as sp:
                sp.set("transport", "frame")
                resp = await wire.serve_batch(vs, wr)
                sp.status = "ok" if resp.status < 400 \
                    else str(resp.status)
                return resp
        if path.startswith("/admin/ec/shard_read"):
            return await self._serve_ec_shard_read(query, headers)
        fid_s = path.lstrip("/")
        try:
            fid = t.FileId.parse(fid_s)
        except ValueError as e:
            return wire.json_err(400, str(e))
        if wc is not None and not self.hop \
                and not wc.owns(fid.volume_id):
            # SO_REUSEPORT handed a pipelining client the wrong
            # worker: forward over the sibling frame channel (token-
            # marked), falling back to FLAG_FALLBACK when the sibling
            # hop is down — the client then retries over HTTP, where
            # the aiohttp routing middleware owns the recovery.
            # CRITICAL: the write/delete gates run HERE first — the
            # sibling channel carries the launch token, so an
            # unguarded forward would launder an external client's
            # write past jwt/whitelist exactly like a real hop
            if method not in ("GET", "HEAD"):
                gate = self._external_mutation_gate(method, query,
                                                   headers)
                if gate is not True:
                    return gate
            return await self._forward_sibling(
                wc.owner_index(fid.volume_id), method, path, query,
                headers, body)
        if method in ("GET", "HEAD"):
            return await self._serve_read(fid_s, method, query, headers)
        if method in ("POST", "PUT"):
            return await self._serve_write(fid_s, query, headers, body)
        if method == "DELETE":
            return await self._serve_delete(fid_s, query, headers)
        return wire.json_err(400, f"method {method} not framed")

    def _external_mutation_gate(self, method: str, query: dict,
                                headers: dict):
        """Write/delete gating for UNTOKENED frame connections, wired
        once for the local-serve and sibling-forward paths, mirroring
        the aiohttp listener's _guarded_request policy: a connection
        with cluster identity (worker token or verified jwt HELLO)
        proceeds — wire.py's per-needle jwt checks still run; on a
        jwt-secured cluster an identity-less mutation answers None =>
        FLAG_FALLBACK (belt-and-braces: the HELLO refusal already
        severed such connections); multipart framing stays
        aiohttp-only; everything else — including replica fan-out
        writes (x-raw-needle) and replicate-typed deletes, which the
        HTTP listeners serve under the same whitelist — is gated on
        -whiteList exactly like HTTP. A whitelist miss is a hard 401.
        Returns True when the mutation may proceed."""
        vs = self.vs
        if self.authed:
            return True
        if vs.jwt_key:
            return None
        if method in ("POST", "PUT") and headers.get(
                "content-type", "").startswith("multipart/"):
            return None
        if not vs.guard.empty and not vs.guard.allows(self.peer_ip):
            return wire.json_err(401, "ip not in whitelist")
        return True

    def _wire_request(self, method: str, fid_s: str, query: dict,
                      headers: dict,
                      body: bytes | None = None) -> wire.WireRequest:
        return wire.WireRequest(
            method=method, fid_s=fid_s, query=query, headers=headers,
            peer_ip=self.peer_ip, body=body, raw=True,
            worker_hop=self.hop)

    async def _serve_read(self, fid_s: str, method: str, query: dict,
                          headers: dict):
        vs = self.vs
        wr = self._wire_request(method, fid_s, query, headers)
        with tracing.start_root("volume", "read",
                                headers=headers) as sp:
            sp.set("transport", "frame")
            resp = await wire.serve_read(vs, wr)
            if resp.upgrade:
                # chunked-manifest assembly (or another aiohttp-only
                # shape): the frame transport cannot stream it
                sp.cancel()
                return None
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            return resp

    async def _serve_write(self, fid_s: str, query: dict, headers: dict,
                           body: bytes):
        vs = self.vs
        wr = self._wire_request("POST", fid_s, query, headers, body)
        if not self.hop:
            # mirror the raw listener's fast-write gate
            gate = self._external_mutation_gate("POST", query, headers)
            if gate is not True:
                return gate
        with tracing.start_root("volume", "write",
                                headers=headers) as sp:
            sp.set("transport", "frame")
            resp = await wire.serve_write(vs, wr)
            if resp.upgrade:
                sp.cancel()
                return None
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            return resp

    async def _serve_delete(self, fid_s: str, query: dict,
                            headers: dict):
        vs = self.vs
        wr = self._wire_request("DELETE", fid_s, query, headers)
        if not self.hop:
            gate = self._external_mutation_gate("DELETE", query,
                                                headers)
            if gate is not True:
                return gate
        with tracing.start_root("volume", "delete",
                                headers=headers) as sp:
            sp.set("transport", "frame")
            resp = await wire.serve_delete(vs, wr)
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            return resp

    async def _serve_ec_shard_read(self, query: dict, headers: dict):
        """Frame twin of h_ec_shard_read's batched form: the EC shard
        gather's one-request-per-holder round trip, minus the HTTP
        envelope."""
        from ..util import batchframe
        vs = self.vs
        try:
            vid = int(query.get("volume", ""))
            reads = batchframe.parse_reads_spec(
                str(query.get("reads", "")))
        except ValueError:
            return wire.json_err(400, "bad reads spec")
        wc = vs.worker_ctx
        if wc is not None and not self.hop and not wc.owns(vid):
            return await self._forward_sibling(
                wc.owner_index(vid), "GET", "/admin/ec/shard_read",
                query, headers, b"")
        with tracing.start_root("volume", "ec.shard_read",
                                headers=headers) as sp:
            sp.set("transport", "frame")
            datas = await vs._in_executor(
                vs.store.read_ec_shard_intervals, vid, reads)
            out = batchframe.encode_shard_rows(reads, datas)
            sp.nbytes = len(out)
            return wire.WireResponse(
                body=out, content_type=batchframe.CONTENT_TYPE)

    async def _forward_sibling(self, owner: int, method: str, path: str,
                               query: dict, headers: dict, body: bytes):
        vs = self.vs
        ch = vs.sibling_frame_channel(owner)
        if ch is None:
            return None
        try:
            status, hdrs, payload = await ch.request(
                method, path, query=query, headers=headers, body=body)
        except FrameChannelError:
            return None
        ct = hdrs.pop("content-type",
                      hdrs.pop("Content-Type", wire.OCTET))
        return wire.WireResponse(status=status, headers=hdrs,
                                 body=payload, content_type=ct)

    # -- response rendering --

    async def _send_fallback(self, req_id: int) -> None:
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.FRAME_FALLBACKS.labels(self._hop_label()).inc()
        async with self._write_lock:
            if not self._closed:
                self.transport.write(encode_frame(
                    RESP, req_id, {"s": 421}, flags=FLAG_FALLBACK))

    async def _send_response(self, req_id: int,
                             resp: wire.WireResponse) -> None:
        if resp.drop:
            # injected connection drop: sever, don't answer
            self._closed = True
            self.transport.close()
            return
        if resp.upgrade or resp.manifest is not None:
            await self._send_fallback(req_id)
            return
        if resp.content_length > MAX_FRAME - (1 << 20):
            # a body this size would exceed the peer decoder's
            # MAX_FRAME and tear the whole multiplexed channel —
            # downgrade this one request to HTTP instead
            if resp.sendfile is not None:
                resp.sendfile.close()
            await self._send_fallback(req_id)
            return
        meta = {"s": resp.status, "h": resp.headers,
                "ct": resp.content_type}
        if resp.truncate_to >= 0:
            # chaos truncate: declared full payload length, partial
            # bytes, dead socket — frame readers see a torn stream
            # exactly like the HTTP listeners' clients
            async with self._write_lock:
                if not self._closed:
                    head = encode_frame(RESP, req_id, meta, resp.body)
                    cut = len(head) - len(resp.body) + resp.truncate_to
                    self.transport.write(head[:cut])
                self._closed = True
                self.transport.close()
            return
        if resp.head:
            # HEAD strips the payload but must still advertise the
            # body length, like the HTTP listeners' Content-Length
            hdrs = dict(resp.headers)
            hdrs.setdefault("Content-Length", str(resp.content_length))
            meta = {"s": resp.status, "h": hdrs,
                    "ct": resp.content_type}
            resp = wire.WireResponse(status=resp.status, headers=hdrs,
                                     content_type=resp.content_type)
        if resp.sendfile is not None:
            await self._send_sendfile(req_id, meta, resp)
            return
        async with self._write_lock:
            if not self._closed:
                self.transport.write(
                    encode_frame(RESP, req_id, meta, resp.body))

    async def _send_sendfile(self, req_id: int, meta: dict,
                             resp: wire.WireResponse) -> None:
        """Zero-copy frame payload: the frame header declares the full
        payload length, then the needle region goes disk->socket with
        loop.sendfile INSIDE the frame (kernel copy; asyncio falls
        back to executor-chunked reads where sendfile is unavailable,
        e.g. TLS transports)."""
        ref = resp.sendfile
        try:
            async with self._write_lock:
                if self._closed:
                    return
                head = encode_frame(RESP, req_id, meta)
                # grow the declared length by the payload to come
                import struct
                length = struct.unpack_from(">I", head)[0] + ref.length
                self.transport.write(
                    struct.pack(">I", length) + head[4:])
                try:
                    await asyncio.get_running_loop().sendfile(
                        self.transport, ref.file, ref.offset,
                        ref.length, fallback=True)
                except (OSError, RuntimeError):
                    # mid-send failure: the declared frame length can
                    # no longer be honored — sever so the peer sees a
                    # torn frame, never a desynced stream
                    self._closed = True
                    self.transport.close()
        finally:
            ref.close()
